// Native runtime pieces for the TPU-native framework's host data path.
//
// Reference analog: dmlc-core's recordio reader + the C++ batch loader of
// iter_image_recordio_2.cc — the parts of the reference IO stack that were
// native C++ and stay native here.  Exposed over a plain C ABI and loaded
// through ctypes (no pybind11 in this image); every entry point releases
// no Python state, so callers may invoke from pool threads without the
// GIL (ctypes drops it around foreign calls).
//
// Build: see native/Makefile (g++ -O2 -shared -fPIC).

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

}  // namespace

extern "C" {

// Scan a .rec file and collect (payload_offset, payload_length) pairs.
// Returns the number of records found, or -1 on malformed framing /
// unreadable file.  offsets/lengths hold up to `cap` entries; extra
// records are counted but not stored (call again with a bigger buffer).
long long tp_recordio_scan(const char* path, long long* offsets,
                           long long* lengths, long long cap) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return -1;
  if (std::fseek(f, 0, SEEK_END) != 0) {
    std::fclose(f);
    return -1;
  }
  const long long fsize = std::ftell(f);
  std::rewind(f);
  long long n = 0;
  uint32_t head[2];
  for (;;) {
    size_t got = std::fread(head, sizeof(uint32_t), 2, f);
    // A short trailing header (writer died mid-header) is treated as
    // EOF, matching the Python scanner's walk — only a bad magic on a
    // *complete* header is malformed framing.
    if (got != 2) break;
    if (head[0] != kMagic) {
      std::fclose(f);
      return -1;
    }
    // upper 3 bits of the length word are the continue flag
    long long len = static_cast<long long>(head[1] & ((1u << 29) - 1));
    long long pos = std::ftell(f);
    // A payload that runs past EOF (writer died mid-record) is a torn
    // tail, not a record: fseek past EOF succeeds on regular files, so
    // bound against the real size instead of trusting the header.
    if (pos + len > fsize) break;
    if (n < cap) {
      offsets[n] = pos;
      lengths[n] = len;
    }
    ++n;
    long long pad = (4 - (len % 4)) % 4;
    if (std::fseek(f, len + pad, SEEK_CUR) != 0) {
      std::fclose(f);
      return -1;
    }
  }
  std::fclose(f);
  return n;
}

// Assemble a batch: for each of n images, transpose an HWC uint8 buffer
// (h*w*c contiguous) into the CHW slot i of `out` (n*c*h*w).  The inner
// transpose is the per-image copy the reference batch loader did in C++
// (iter_batchloader.h) — GIL-free here so decode-pool threads overlap.
void tp_assemble_chw_u8(const uint8_t** imgs, int64_t n, int64_t h,
                        int64_t w, int64_t c, uint8_t* out) {
  const int64_t plane = h * w;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* src = imgs[i];
    uint8_t* dst = out + i * c * plane;
    for (int64_t p = 0; p < plane; ++p) {
      const uint8_t* px = src + p * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        dst[ch * plane + p] = px[ch];
      }
    }
  }
}

// Same, float32 output with optional per-channel mean/std normalize
// (mean/std may be null).
void tp_assemble_chw_f32(const uint8_t** imgs, int64_t n, int64_t h,
                         int64_t w, int64_t c, const float* mean,
                         const float* inv_std, float* out) {
  const int64_t plane = h * w;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* src = imgs[i];
    float* dst = out + i * c * plane;
    for (int64_t ch = 0; ch < c; ++ch) {
      const float m = mean ? mean[ch] : 0.0f;
      const float s = inv_std ? inv_std[ch] : 1.0f;
      float* d = dst + ch * plane;
      const uint8_t* sp = src + ch;
      for (int64_t p = 0; p < plane; ++p) {
        d[p] = (static_cast<float>(sp[p * c]) - m) * s;
      }
    }
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// JPEG decode + resize + crop + flip (the reference's C++ image pipeline
// stage, iter_image_recordio_2.cc decode path).  libjpeg for the decode,
// bilinear resize, all in one GIL-free call per image.  Compiled only
// with -DTP_WITH_JPEG -ljpeg (native.py tries that first and falls back
// to a decoder-less build when jpeg dev files are absent — the symbol
// is then missing and Python keeps its cv2 path).
// ---------------------------------------------------------------------------
#ifdef TP_WITH_JPEG
#include <csetjmp>
#include <cstdlib>
#include <vector>

#include <jpeglib.h>

namespace {

struct TpJpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void tp_jpeg_fail(j_common_ptr cinfo) {
  TpJpegErr* err = reinterpret_cast<TpJpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

// bilinear uint8 RGB resize (src HWC -> dst HWC)
void tp_resize_bilinear(const uint8_t* src, int sh, int sw,
                        uint8_t* dst, int dh, int dw) {
  const float ry = dh > 1 ? (sh - 1.0f) / (dh - 1.0f) : 0.0f;
  const float rx = dw > 1 ? (sw - 1.0f) / (dw - 1.0f) : 0.0f;
  for (int y = 0; y < dh; ++y) {
    const float fy = y * ry;
    const int y0 = static_cast<int>(fy);
    const int y1 = y0 + 1 < sh ? y0 + 1 : y0;
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      const float fx = x * rx;
      const int x0 = static_cast<int>(fx);
      const int x1 = x0 + 1 < sw ? x0 + 1 : x0;
      const float wx = fx - x0;
      const uint8_t* p00 = src + (y0 * sw + x0) * 3;
      const uint8_t* p01 = src + (y0 * sw + x1) * 3;
      const uint8_t* p10 = src + (y1 * sw + x0) * 3;
      const uint8_t* p11 = src + (y1 * sw + x1) * 3;
      uint8_t* d = dst + (y * dw + x) * 3;
      for (int c = 0; c < 3; ++c) {
        const float top = p00[c] + (p01[c] - p00[c]) * wx;
        const float bot = p10[c] + (p11[c] - p10[c]) * wx;
        d[c] = static_cast<uint8_t>(top + (bot - top) * wy + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Decode a JPEG buffer to RGB, optionally resize so the SHORTER side is
// `resize` (bilinear), crop out_h x out_w at (crop_y, crop_x) (-1, -1 =
// center), optionally mirror horizontally; write HWC uint8 into `out`
// (out_h*out_w*3).  Returns the packed post-resize dims
// (ih << 32) | iw on success (always > 0), -1 on decode error, -2 if
// the crop falls out of bounds (caller retries with the python path).
// One call per image; no Python state touched (ctypes drops the GIL
// around the call).
long long tp_decode_resize_crop(const unsigned char* buf, long long len,
                                long long resize, long long out_h,
                                long long out_w, long long crop_y,
                                long long crop_x, long long flip,
                                unsigned char* out) {
  jpeg_decompress_struct cinfo;
  TpJpegErr err;
  // pixel buffers live OUTSIDE the setjmp region: a longjmp from the
  // scanline loop across non-trivially-destructible locals is UB and
  // leaks the allocations; declared here they survive the jump and
  // destruct normally on return
  std::vector<uint8_t> raw;
  std::vector<uint8_t> resized;
  cinfo.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = tp_jpeg_fail;
  if (setjmp(err.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int sw = cinfo.output_width, sh = cinfo.output_height;
  raw.resize(static_cast<size_t>(sw) * sh * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = raw.data() + static_cast<size_t>(
        cinfo.output_scanline) * sw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  const uint8_t* img = raw.data();
  int ih = sh, iw = sw;
  if (resize > 0 && (sh < sw ? sh : sw) != resize) {
    if (sh < sw) {
      ih = static_cast<int>(resize);
      iw = static_cast<int>(sw * static_cast<double>(resize) / sh);
    } else {
      iw = static_cast<int>(resize);
      ih = static_cast<int>(sh * static_cast<double>(resize) / sw);
    }
    resized.resize(static_cast<size_t>(ih) * iw * 3);
    tp_resize_bilinear(raw.data(), sh, sw, resized.data(), ih, iw);
    img = resized.data();
  }

  long long cy = crop_y, cx = crop_x;
  if (cy < 0) cy = (ih - out_h) / 2;
  if (cx < 0) cx = (iw - out_w) / 2;
  if (cy < 0 || cx < 0 || cy + out_h > ih || cx + out_w > iw) return -2;
  for (long long y = 0; y < out_h; ++y) {
    const uint8_t* srow = img + ((cy + y) * iw + cx) * 3;
    uint8_t* drow = out + y * out_w * 3;
    if (flip) {
      for (long long x = 0; x < out_w; ++x) {
        const uint8_t* p = srow + (out_w - 1 - x) * 3;
        drow[x * 3 + 0] = p[0];
        drow[x * 3 + 1] = p[1];
        drow[x * 3 + 2] = p[2];
      }
    } else {
      std::memcpy(drow, srow, static_cast<size_t>(out_w) * 3);
    }
  }
  return (static_cast<long long>(ih) << 32) | iw;
}

// Transcode for pack time (the native im2rec stage, reference
// tools/im2rec.cc:1-302): decode a JPEG, bilinear-resize the SHORTER
// side to `resize` (0 = keep), re-encode at `quality` into `out`
// (capacity `cap`).  Returns bytes written, -1 decode/encode error,
// -3 capacity too small.
long long tp_transcode_jpeg(const unsigned char* buf, long long len,
                            long long resize, long long quality,
                            unsigned char* out, long long cap) {
  jpeg_decompress_struct din;
  TpJpegErr derr;
  // see tp_decode_resize_crop: buffers outside the setjmp region so a
  // decode-error longjmp cannot skip their destructors
  std::vector<uint8_t> raw;
  std::vector<uint8_t> resized;
  din.err = jpeg_std_error(&derr.mgr);
  derr.mgr.error_exit = tp_jpeg_fail;
  if (setjmp(derr.jb)) {
    jpeg_destroy_decompress(&din);
    return -1;
  }
  jpeg_create_decompress(&din);
  jpeg_mem_src(&din, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&din, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&din);
    return -1;
  }
  din.out_color_space = JCS_RGB;
  jpeg_start_decompress(&din);
  const int sw = din.output_width, sh = din.output_height;
  raw.resize(static_cast<size_t>(sw) * sh * 3);
  while (din.output_scanline < din.output_height) {
    uint8_t* row = raw.data() + static_cast<size_t>(
        din.output_scanline) * sw * 3;
    jpeg_read_scanlines(&din, &row, 1);
  }
  jpeg_finish_decompress(&din);
  jpeg_destroy_decompress(&din);

  const uint8_t* img = raw.data();
  int ih = sh, iw = sw;
  if (resize > 0 && (sh < sw ? sh : sw) != resize) {
    if (sh < sw) {
      ih = static_cast<int>(resize);
      iw = static_cast<int>(sw * static_cast<double>(resize) / sh);
    } else {
      iw = static_cast<int>(resize);
      ih = static_cast<int>(sh * static_cast<double>(resize) / sw);
    }
    resized.resize(static_cast<size_t>(ih) * iw * 3);
    tp_resize_bilinear(raw.data(), sh, sw, resized.data(), ih, iw);
    img = resized.data();
  }

  jpeg_compress_struct cout_;
  TpJpegErr eerr;
  unsigned char* mem = nullptr;
  unsigned long memlen = 0;
  cout_.err = jpeg_std_error(&eerr.mgr);
  eerr.mgr.error_exit = tp_jpeg_fail;
  if (setjmp(eerr.jb)) {
    jpeg_destroy_compress(&cout_);
    if (mem != nullptr) free(mem);
    return -1;
  }
  jpeg_create_compress(&cout_);
  jpeg_mem_dest(&cout_, &mem, &memlen);
  cout_.image_width = iw;
  cout_.image_height = ih;
  cout_.input_components = 3;
  cout_.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cout_);
  jpeg_set_quality(&cout_, static_cast<int>(quality), TRUE);
  jpeg_start_compress(&cout_, TRUE);
  while (cout_.next_scanline < cout_.image_height) {
    const uint8_t* row = img + static_cast<size_t>(
        cout_.next_scanline) * iw * 3;
    uint8_t* rows[1] = {const_cast<uint8_t*>(row)};
    jpeg_write_scanlines(&cout_, rows, 1);
  }
  jpeg_finish_compress(&cout_);
  jpeg_destroy_compress(&cout_);
  long long n = static_cast<long long>(memlen);
  if (n > cap) {
    free(mem);
    return -3;
  }
  std::memcpy(out, mem, static_cast<size_t>(n));
  free(mem);
  return n;
}

}  // extern "C"
#endif  // TP_WITH_JPEG
