// Native runtime pieces for the TPU-native framework's host data path.
//
// Reference analog: dmlc-core's recordio reader + the C++ batch loader of
// iter_image_recordio_2.cc — the parts of the reference IO stack that were
// native C++ and stay native here.  Exposed over a plain C ABI and loaded
// through ctypes (no pybind11 in this image); every entry point releases
// no Python state, so callers may invoke from pool threads without the
// GIL (ctypes drops it around foreign calls).
//
// Build: see native/Makefile (g++ -O2 -shared -fPIC).

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

}  // namespace

extern "C" {

// Scan a .rec file and collect (payload_offset, payload_length) pairs.
// Returns the number of records found, or -1 on malformed framing /
// unreadable file.  offsets/lengths hold up to `cap` entries; extra
// records are counted but not stored (call again with a bigger buffer).
long long tp_recordio_scan(const char* path, long long* offsets,
                           long long* lengths, long long cap) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return -1;
  if (std::fseek(f, 0, SEEK_END) != 0) {
    std::fclose(f);
    return -1;
  }
  const long long fsize = std::ftell(f);
  std::rewind(f);
  long long n = 0;
  uint32_t head[2];
  for (;;) {
    size_t got = std::fread(head, sizeof(uint32_t), 2, f);
    // A short trailing header (writer died mid-header) is treated as
    // EOF, matching the Python scanner's walk — only a bad magic on a
    // *complete* header is malformed framing.
    if (got != 2) break;
    if (head[0] != kMagic) {
      std::fclose(f);
      return -1;
    }
    // upper 3 bits of the length word are the continue flag
    long long len = static_cast<long long>(head[1] & ((1u << 29) - 1));
    long long pos = std::ftell(f);
    // A payload that runs past EOF (writer died mid-record) is a torn
    // tail, not a record: fseek past EOF succeeds on regular files, so
    // bound against the real size instead of trusting the header.
    if (pos + len > fsize) break;
    if (n < cap) {
      offsets[n] = pos;
      lengths[n] = len;
    }
    ++n;
    long long pad = (4 - (len % 4)) % 4;
    if (std::fseek(f, len + pad, SEEK_CUR) != 0) {
      std::fclose(f);
      return -1;
    }
  }
  std::fclose(f);
  return n;
}

// Assemble a batch: for each of n images, transpose an HWC uint8 buffer
// (h*w*c contiguous) into the CHW slot i of `out` (n*c*h*w).  The inner
// transpose is the per-image copy the reference batch loader did in C++
// (iter_batchloader.h) — GIL-free here so decode-pool threads overlap.
void tp_assemble_chw_u8(const uint8_t** imgs, int64_t n, int64_t h,
                        int64_t w, int64_t c, uint8_t* out) {
  const int64_t plane = h * w;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* src = imgs[i];
    uint8_t* dst = out + i * c * plane;
    for (int64_t p = 0; p < plane; ++p) {
      const uint8_t* px = src + p * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        dst[ch * plane + p] = px[ch];
      }
    }
  }
}

// Same, float32 output with optional per-channel mean/std normalize
// (mean/std may be null).
void tp_assemble_chw_f32(const uint8_t** imgs, int64_t n, int64_t h,
                         int64_t w, int64_t c, const float* mean,
                         const float* inv_std, float* out) {
  const int64_t plane = h * w;
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* src = imgs[i];
    float* dst = out + i * c * plane;
    for (int64_t ch = 0; ch < c; ++ch) {
      const float m = mean ? mean[ch] : 0.0f;
      const float s = inv_std ? inv_std[ch] : 1.0f;
      float* d = dst + ch * plane;
      const uint8_t* sp = src + ch;
      for (int64_t p = 0; p < plane; ++p) {
        d[p] = (static_cast<float>(sp[p * c]) - m) * s;
      }
    }
  }
}

}  // extern "C"
