"""int8 weight-only quantization (docs/quantization.md).

The serving-decode insight of Dettmers et al., *LLM.int8()* (2022),
restricted to the part that is free on TPU: weights quantized **per
output channel** with symmetric scales, activations left in float, and
the dequant folded into the matmul epilogue —

    y = (x · qᵀ) * scale        ≡        x · (q * scale[:, None])ᵀ

so batch-1 decode, which is weight-bandwidth-bound by construction,
reads half the HBM bytes while XLA fuses the int8→float convert into
the matmul's operand read.  Quantization happens ONCE at load time;
nothing requantizes on the hot path.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["quantize_rowwise", "dequantize_rowwise", "Int8Weight",
           "int8_matmul"]


def quantize_rowwise(w):
    """Per-output-channel symmetric int8 quantization of a (N, K) float
    weight.  Returns ``(q int8 (N,K), scale f32 (N,))`` with
    ``q * scale[:, None] ≈ w``; all-zero rows get scale 1 (q = 0)."""
    w = np.asarray(w, dtype=np.float32)
    if w.ndim != 2:
        raise ValueError("quantize_rowwise expects a 2-D (N, K) weight, "
                         "got shape %s" % (w.shape,))
    amax = np.max(np.abs(w), axis=1)
    scale = np.where(amax > 0.0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale[:, None]), -127, 127).astype(np.int8)
    return q, scale


def dequantize_rowwise(q, scale):
    """Exact inverse of the stored representation (not of the original
    float weight — quantization rounds)."""
    return np.asarray(q, np.float32) * np.asarray(scale, np.float32)[:, None]


class Int8Weight:
    """Device-resident quantized weight: int8 values + f32 per-row scale.

    Stored instead of the float array in a params dict; ``serving``'s
    ``_fc`` dispatches on it.  ``nbytes`` reflects what actually sits in
    HBM (the telemetry ``quant_weight_bytes`` gauge sums it)."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return int(self.q.size) + 4 * int(self.scale.size)

    def dequantize(self, dtype=jnp.float32):
        return (self.q.astype(dtype) * self.scale.astype(dtype)[:, None])


def int8_matmul(x, w: Int8Weight):
    """``x · wᵀ`` with the dequant fused into the matmul epilogue:
    int8 weight upcast to the activation dtype inside the contraction
    (XLA fuses the convert into the operand read), per-row scale applied
    to the (..., N) output columns."""
    y = jnp.matmul(x, w.q.T.astype(x.dtype))
    return y * w.scale.astype(y.dtype)
