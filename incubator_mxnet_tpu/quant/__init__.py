"""Quantized compute paths (docs/quantization.md).

Two independent byte-halving levers, both off by default:

- **fp8 matmul training** (``TP_MATMUL_DTYPE=fp8``): every
  ``FullyConnected`` matmul inside ``FusedTrainStep`` runs through
  :func:`fp8.scaled_dot` — e4m3 forward / e5m2 backward casts with
  delayed per-tensor amax scaling, f32 masters untouched.
- **int8 weight-only serving** (``TP_SERVE_WEIGHT_DTYPE=int8``):
  transformer weights stored int8 + per-output-channel scale in HBM,
  dequant fused into the decode matmul (:mod:`.int8`).

The training hook works by *interception*, not graph rewrite: the
``FullyConnected`` op calls :func:`site_dot` for its matmul.  With no
context installed that is a plain ``jnp.matmul(x, w.T)`` — bit-identical
to the pre-quantization op — so the default path carries zero risk.
``FusedTrainStep`` installs an :class:`FP8Sites` collector around the
lowered forward; sites are consumed in trace order, which for the
symbol interpreter equals topo order, so site *i* is the same layer
every step and its amax history is coherent.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..base import MXNetError
from . import fp8, int8
from .fp8 import Recipe, scaled_dot
from .int8 import Int8Weight, int8_matmul, quantize_rowwise

__all__ = ["fp8", "int8", "Recipe", "scaled_dot", "Int8Weight",
           "int8_matmul", "quantize_rowwise", "FP8Sites",
           "matmul_context", "site_dot"]

_TLS = threading.local()


class FP8Sites:
    """Trace-time collector for one forward trace: hands each
    ``FullyConnected`` matmul its per-site amax state in consumption
    order and accumulates the refreshed states."""

    def __init__(self, states, recipe=None):
        self.states = tuple(states)
        self.recipe = recipe or fp8.default_recipe()
        self.new_states = []

    def dot(self, x, w):
        i = len(self.new_states)
        if i >= len(self.states):
            raise MXNetError(
                "fp8 matmul context: the forward trace hit more "
                "FullyConnected sites than the %d planned from the symbol "
                "graph — the trace is not replay-stable (remat?)"
                % len(self.states))
        y, new = scaled_dot(x, w, self.states[i], self.recipe)
        self.new_states.append(new)
        return y


@contextlib.contextmanager
def matmul_context(sites: FP8Sites):
    """Install ``sites`` as the active quantized-matmul context for
    FullyConnected tracing on this thread."""
    prev = getattr(_TLS, "sites", None)
    _TLS.sites = sites
    try:
        yield sites
    finally:
        _TLS.sites = prev


def site_dot(x, w):
    """The FullyConnected matmul: ``x · wᵀ`` in ``x.dtype``.  Routed
    through the active quantized context when one is installed;
    otherwise a plain ``jnp.matmul`` — bit-identical to the
    pre-quantization op, so the default path is unchanged."""
    sites = getattr(_TLS, "sites", None)
    if sites is None:
        return jnp.matmul(x, w.T)
    return sites.dot(x, w)
