"""fp8 matmul with delayed per-tensor amax scaling (docs/quantization.md).

The recipe is Micikevicius et al., *FP8 Formats for Deep Learning* (2022):
activations and weights cast to e4m3 in the forward pass, gradients to
e5m2 in the backward, each with a per-tensor scale derived from a rolling
window of past amax observations ("delayed scaling" — the scale used at
step t comes from steps < t, so the cast needs no extra pass over the
tensor).  Master weights, optimizer state and the loss stay f32; only the
three matmul operand casts change.

Two dot backends:

- **native** — feed fp8 operands straight to ``lax.dot_general`` with
  ``preferred_element_type=f32`` (TPU/GPU with fp8 MXU support);
- **emulation** — upcast the fp8 values to bf16 and dot in bf16/f32.
  Numerically this applies the SAME value quantization (the fp8 rounding
  happened at the cast), so convergence behavior is representative on
  any backend — including the CPU tier-1 mesh — while the speed win is
  native-only.

``scaled_dot`` is a ``jax.custom_vjp``: its state argument threads the
amax histories through the step function, and the *backward* pass returns
the updated gradient history as the state cotangent — the only way a
quantity first observed during backprop can escape ``jax.vjp``.  Callers
merge: forward histories from the primal output, gradient history from
the state cotangent (see ``parallel/fused.py``).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..base import get_env

__all__ = [
    "E4M3_MAX", "E5M2_MAX", "Recipe", "default_recipe", "native_fp8_dot",
    "init_site_state", "compute_scale", "saturating_cast", "scaled_dot",
]

# largest finite values of the two fp8 formats (OCP FP8 spec: e4m3fn has
# no inf, max=448; e5m2 keeps inf/nan, max finite=57344)
E4M3_MAX = 448.0
E5M2_MAX = 57344.0

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2


def native_fp8_dot() -> bool:
    """Whether to hand fp8 operands to the MXU directly.  ``TP_FP8_NATIVE``
    forces (1) or forbids (0); default: native on TPU, emulate elsewhere."""
    ov = get_env("FP8_NATIVE", "auto")
    if ov is not None and str(ov) not in ("", "auto"):
        return str(ov) not in ("0", "false", "False")
    return jax.default_backend() == "tpu"


class Recipe:
    """Static (trace-time) fp8 configuration: amax-history length,
    safety margin on the scale, and the dot backend."""

    __slots__ = ("history", "margin", "native")

    def __init__(self, history=None, margin=None, native=None):
        self.history = int(history if history is not None
                           else get_env("FP8_HISTORY", 16, int))
        self.margin = float(margin if margin is not None
                            else get_env("FP8_MARGIN", 1.0, float))
        self.native = native_fp8_dot() if native is None else bool(native)
        if self.history < 1:
            raise ValueError("fp8 amax history must be >= 1, got %d"
                             % self.history)

    def __repr__(self):
        return ("Recipe(history=%d, margin=%g, native=%s)"
                % (self.history, self.margin, self.native))


_DEFAULT = None


def default_recipe() -> Recipe:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Recipe()
    return _DEFAULT


def init_site_state(recipe: Recipe):
    """Fresh per-site state: one amax-history vector per operand role.
    All-zero history ⇒ scale 1.0 ⇒ the first step quantizes unscaled
    (safe: e4m3 covers ±448, far beyond init-time activations)."""
    z = jnp.zeros((recipe.history,), jnp.float32)
    return {"x": z, "w": z, "g": z}


def compute_scale(history, fp8_max, margin=1.0):
    """Delayed scale from the amax window: map the largest recent |value|
    to ``fp8_max / margin``.  All-zero history (startup) ⇒ 1.0."""
    amax = jnp.max(history)
    return jnp.where(amax > 0.0, amax * margin / fp8_max, 1.0)


def saturating_cast(x, scale, fp8_max, dtype):
    """Divide by scale, clip to the format's finite range, then cast.
    The clip matters: e5m2 overflows to inf and e4m3fn to nan without
    it, and one stale-history outlier would poison the step."""
    y = x.astype(jnp.float32) / scale
    return jnp.clip(y, -fp8_max, fp8_max).astype(dtype)


def _roll(history, x):
    """Record the current tensor's amax at the head of the window."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32))).reshape(1)
    return jnp.concatenate([amax, history[:-1]])


def _qdot(a, b, contract, native):
    """dot_general over fp8 operands with f32 accumulation; the emulation
    path upcasts to bf16 first (same quantized values, portable dot)."""
    if not native:
        a = a.astype(jnp.bfloat16)
        b = b.astype(jnp.bfloat16)
    return jax.lax.dot_general(a, b, dimension_numbers=(contract, ((), ())),
                               preferred_element_type=jnp.float32)


def _fwd_impl(recipe, x, w, state):
    sx = compute_scale(state["x"], E4M3_MAX, recipe.margin)
    sw = compute_scale(state["w"], E4M3_MAX, recipe.margin)
    qx = saturating_cast(x, sx, E4M3_MAX, E4M3)
    qw = saturating_cast(w, sw, E4M3_MAX, E4M3)
    # FC layout: x (..., K) · w (N, K) → (..., N)
    y = _qdot(qx, qw, ((x.ndim - 1,), (w.ndim - 1,)), recipe.native)
    y = (y * (sx * sw)).astype(x.dtype)
    new_state = {"x": _roll(state["x"], x), "w": _roll(state["w"], w),
                 "g": state["g"]}
    return y, new_state, (qx, qw, sx, sw, state["g"])


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _scaled_dot(recipe, x, w, state):
    y, new_state, _ = _fwd_impl(recipe, x, w, state)
    return y, new_state


def _scaled_dot_fwd(recipe, x, w, state):
    y, new_state, res = _fwd_impl(recipe, x, w, state)
    # dtype-only sentinels: residuals must be jax types, not np.dtype
    return (y, new_state), res + (jnp.zeros((), x.dtype),
                                  jnp.zeros((), w.dtype))


def _scaled_dot_bwd(recipe, res, ct):
    qx, qw, sx, sw, ghist, x_proto, w_proto = res
    x_dtype, w_dtype = x_proto.dtype, w_proto.dtype
    gy, _ = ct  # the state cotangent is seeded with zeros by the caller
    sg = compute_scale(ghist, E5M2_MAX, recipe.margin)
    qg = saturating_cast(gy, sg, E5M2_MAX, E5M2)
    # dx (..., K) = gy (..., N) · w (N, K)
    dx = _qdot(qg, qw, ((qg.ndim - 1,), (0,)), recipe.native) * (sg * sw)
    # dw (N, K) = Σ_batch gy ⊗ x
    bd = tuple(range(qx.ndim - 1))
    dw = _qdot(qg, qx, (bd, bd), recipe.native) * (sg * sx)
    zeros = jnp.zeros_like(ghist)
    dstate = {"x": zeros, "w": zeros, "g": _roll(ghist, gy)}
    return dx.astype(x_dtype), dw.astype(w_dtype), dstate


_scaled_dot.defvjp(_scaled_dot_fwd, _scaled_dot_bwd)


def scaled_dot(x, w, state, recipe=None):
    """fp8 ``x · wᵀ`` with delayed per-tensor scaling.

    Returns ``(y, new_state)`` where ``y`` is in ``x.dtype`` and
    ``new_state`` carries the refreshed x/w amax histories (``g`` passes
    through — under ``jax.vjp`` the gradient history arrives separately
    as the cotangent of ``state``)."""
    return _scaled_dot(recipe or default_recipe(), x, w, state)
