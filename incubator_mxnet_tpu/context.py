"""Device context model.

Mirrors the reference's ``Context`` (``include/mxnet/base.h:53-…``,
``python/mxnet/context.py``): ``mx.cpu()``, ``mx.tpu(i)`` (the reference's
``mx.gpu(i)`` aliases to TPU here so reference scripts run unmodified), and
``with ctx:`` scoping.

TPU-first design: a Context resolves to a concrete ``jax.Device``.  When the
requested platform is unavailable (e.g. tests forced onto CPU with
``JAX_PLATFORMS=cpu``), accelerator contexts transparently fall back to host
devices — this mirrors the reference test strategy where "CPU Context stands
in for any device" in graph-partition tests (SURVEY.md §4).
"""
from __future__ import annotations

import threading
from typing import Optional

from .base import MXNetError

__all__ = ["Context", "cpu", "tpu", "gpu", "cpu_pinned", "current_context",
           "num_tpus", "num_gpus"]


class Context:
    """A device context ``(device_type, device_id)``."""

    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 4: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3,
                   "cpu_shared": 4}
    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError("unknown device type %s" % device_type)
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional["Context"] = None

    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    # -- `with ctx:` scoping (python/mxnet/context.py:80-96 equivalent) -----
    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- jax resolution ----------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        tpu → jax accelerator device[i] when present, else host device[i]
        (CPU stand-in, as in the reference multi-device tests).  cpu → host
        device[i % n] so cpu(0)/cpu(1) shard graphs even on one host.
        """
        import jax

        if self.device_type in ("tpu",):
            accel = _accelerator_devices()
            if accel:
                return accel[self.device_id % len(accel)]
            host = _local_cpu_devices()
            return host[self.device_id % len(host)]
        host = _local_cpu_devices() or jax.local_devices()
        return host[self.device_id % len(host)]

    def empty_cache(self):
        """Best-effort device allocator cache release (reference
        ``Context::empty_cache`` analog — XLA owns the allocator, so this is
        advisory)."""
        import gc

        gc.collect()


def _accelerator_devices():
    # local (addressable) devices only: in a multi-process job each rank
    # must place data on its own devices, never a peer's
    import jax

    return [d for d in jax.local_devices() if d.platform != "cpu"]


def _local_cpu_devices():
    # the host backend must be requested explicitly: under an accelerator
    # platform ``jax.local_devices()`` lists only accelerator chips, and
    # falling back to them would silently place the "cpu" context (and with
    # it the whole host-side data pipeline) on the accelerator
    import jax

    try:
        return list(jax.local_devices(backend="cpu"))
    except RuntimeError:
        try:
            return [d for d in jax.local_devices() if d.platform == "cpu"]
        except RuntimeError:
            return []


def _has_cpu() -> bool:
    return bool(_local_cpu_devices())


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


#: Reference-compat alias — ``mx.gpu(i)`` maps to the TPU device.
gpu = tpu


def num_tpus() -> int:
    return len(_accelerator_devices())


num_gpus = num_tpus


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
