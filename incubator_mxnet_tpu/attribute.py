"""Attribute scoping (``python/mxnet/attribute.py``).

``with mx.AttrScope(ctx_group='dev1'):`` attaches attrs (notably
``ctx_group`` for the group2ctx model-parallel mechanism,
SURVEY.md §2.4) to every symbol created in scope.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_state = threading.local()


class AttrScope:
    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("AttrScope attr values must be str")
        self._attr = kwargs
        self._old = None

    def get(self, attr):
        """Merge scope attrs into user attrs (user wins)."""
        if not self._attr:
            return attr or {}
        ret = dict(self._attr)
        if attr:
            ret.update(attr)
        return ret

    def __enter__(self):
        if not hasattr(_state, "stack"):
            _state.stack = [AttrScope()]
        merged = dict(_state.stack[-1]._attr)
        merged.update(self._attr)
        scope = AttrScope.__new__(AttrScope)
        scope._attr = merged
        _state.stack.append(scope)
        return self

    def __exit__(self, *exc):
        _state.stack.pop()


def current() -> AttrScope:
    if not hasattr(_state, "stack"):
        _state.stack = [AttrScope()]
    return _state.stack[-1]
