"""Model helpers (``python/mxnet/model.py``): checkpoint save/load and the
kvstore plumbing Module uses (_create_kvstore, _initialize_kvstore,
_update_params[_on_kvstore])."""
from __future__ import annotations

import logging
import os
from collections import namedtuple
from typing import Dict, List, Optional, Tuple

from . import kvstore as kvs
from . import symbol as sym_mod
from .base import MXNetError
from .ndarray import load as nd_load, save as nd_save
from .ndarray.ndarray import NDArray

__all__ = ["BatchEndParam", "FeedForward", "save_checkpoint",
           "load_checkpoint", "_create_kvstore", "_initialize_kvstore",
           "_update_params_on_kvstore", "_update_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _atomic_write(path: str, writer) -> None:
    """Write ``path`` via a same-directory temp file + ``os.replace`` so a
    crash mid-write never leaves a truncated file under the final name.
    Non-local URIs (``://``) bypass this — ``os.replace`` is local-only."""
    if "://" in path:
        writer(path)
        return
    tmp = "%s.tmp.%d" % (path, os.getpid())
    try:
        writer(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict) -> None:
    """Two-file checkpoint: ``prefix-symbol.json`` + ``prefix-%04d.params``
    (reference ``model.py:340``; NDArray container format analog of
    ``src/ndarray/ndarray.cc:668``).  Both files are written atomically
    (temp file + rename) so a preempted save cannot corrupt an existing
    checkpoint under the same name."""
    if symbol is not None:
        _atomic_write("%s-symbol.json" % prefix, symbol.save)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    _atomic_write(param_name, lambda p: nd_save(p, save_dict))
    logging.info("Saved checkpoint to \"%s\"", param_name)


def load_checkpoint(prefix: str, epoch: int):
    """Returns (symbol, arg_params, aux_params)
    (reference ``model.py:370``)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


def _create_kvstore(kvstore, num_device: int, arg_params):
    """Choose kvstore + whether the optimizer update runs inside it
    (reference ``model.py`` _create_kvstore)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(np_prod(p.shape))
                               for p in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def np_prod(shape):
    out = 1
    for s in shape:
        out *= s
    return out


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore: bool) -> None:
    """kv.init each param; distributed pull of initial weights
    (reference ``model.py:96``)."""
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore,
                              param_names) -> None:
    """push grad, pull weight per key (reference ``model.py:106``)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device,
                   kvstore=None, param_names=None) -> None:
    """Aggregate via kvstore (store-only) then run the updater per device
    (reference ``model.py:118``)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            name = param_names[index]
            kvstore.push(name, grad_list, priority=-index)
            kvstore.pull(name, grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


class FeedForward:
    """Legacy estimator API (reference ``model.py:408`` ``FeedForward``):
    scikit-style ``fit(X, y)`` / ``predict(X)`` over a symbol.  Internally
    drives the Module stack (the reference drove
    ``DataParallelExecutorManager``; Module supersedes it there too).
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .context import cpu
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx if ctx is not None else [cpu()]
        if not isinstance(self.ctx, list):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.kwargs = kwargs.copy()
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None \
            else Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.argument_checked = False
        self._pred_exec = None

    def _check_arguments(self):
        if self.argument_checked:
            return
        from .executor_manager import _check_arguments
        assert self.symbol is not None
        self.argument_checked = True
        _check_arguments(self.symbol)
        if self.allow_extra_params and self.arg_params:
            arg_names = set(self.symbol.list_arguments())
            self.arg_params = {k: v for k, v in self.arg_params.items()
                               if k in arg_names}
        if self.allow_extra_params and self.aux_params:
            aux_names = set(self.symbol.list_auxiliary_states())
            self.aux_params = {k: v for k, v in self.aux_params.items()
                               if k in aux_names}

    @staticmethod
    def _is_data_arg(name):
        return name.endswith("data") or name.endswith("label")

    def _init_iter(self, X, y, is_train):
        """Coerce numpy/NDArray input into a DataIter
        (reference ``model.py:583``)."""
        import numpy as np

        from .io import DataIter, NDArrayIter
        from .ndarray.ndarray import NDArray

        if isinstance(X, DataIter) or (hasattr(X, "provide_data") and
                                       hasattr(X, "reset")):
            return X
        if isinstance(X, NDArray):
            X = X.asnumpy()
        if isinstance(y, NDArray):
            y = y.asnumpy()
        if not isinstance(X, np.ndarray):
            raise TypeError("X must be DataIter, NDArray or numpy.ndarray")
        if y is None:
            if is_train:
                raise ValueError("y must be specified when X is numpy")
            y = np.zeros(X.shape[0])
        y = np.asarray(y)
        if y.ndim == 2 and y.shape[1] == 1:
            y = y.flatten()
        batch_size = min(X.shape[0], self.numpy_batch_size)
        return NDArrayIter(X, y, batch_size=batch_size, shuffle=is_train,
                           last_batch_handle="roll_over" if is_train
                           else "pad")

    def _init_eval_iter(self, eval_data):
        if eval_data is None:
            return eval_data
        if isinstance(eval_data, (tuple, list)) and len(eval_data) == 2:
            return self._init_iter(eval_data[0], eval_data[1],
                                   is_train=True)
        return eval_data

    def _make_module(self, data_iter, logger=None, work_load_list=None):
        import logging as _logging

        from .module import Module

        data_names = [d[0] for d in data_iter.provide_data]
        label_names = [l[0] for l in (data_iter.provide_label or [])]
        return Module(self.symbol, data_names=data_names,
                      label_names=label_names, context=self.ctx,
                      logger=logger or _logging,
                      work_load_list=work_load_list)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None):
        """Train (reference ``model.py:748``)."""
        data = self._init_iter(X, y, is_train=True)
        eval_data = self._init_eval_iter(eval_data)
        self._check_arguments()

        mod = self._make_module(data, logger=logger,
                                work_load_list=work_load_list)
        mod.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=dict(self.kwargs),
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                allow_missing=(self.arg_params is None),
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback)
        self.arg_params, self.aux_params = mod.get_params()
        self._pred_exec = None
        return self

    def _init_predictor(self, data_iter):
        """Bind (and cache) the inference module — avoids recompiling the
        XLA program on every predict/score call (reference
        ``model.py:567`` cached ``_pred_exec``)."""
        key = tuple(tuple(d) for d in data_iter.provide_data)
        if self._pred_exec is not None and self._pred_exec[0] == key:
            return self._pred_exec[1]
        mod = self._make_module(data_iter)
        mod.bind(data_shapes=data_iter.provide_data,
                 label_shapes=data_iter.provide_label, for_training=False)
        mod.set_params(self.arg_params or {}, self.aux_params or {},
                       allow_missing=(self.arg_params is None))
        self._pred_exec = (key, mod)
        return mod

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Batched inference; returns numpy (reference ``model.py:628``)."""
        import numpy as np

        data = self._init_iter(X, None, is_train=False)
        self._check_arguments()
        if reset:
            data.reset()
        mod = self._init_predictor(data)

        outputs = []
        data_list, label_list = [], []
        for i, batch in enumerate(data):
            if num_batch is not None and i == num_batch:
                break
            mod.forward(batch, is_train=False)
            pad = batch.pad
            outs = [o[0:o.shape[0] - pad].asnumpy()
                    for o in mod.get_outputs()]
            outputs.append(outs)
            if return_data:
                data_list.append(batch.data[0][0:batch.data[0].shape[0]
                                               - pad].asnumpy())
                if batch.label:
                    label_list.append(
                        batch.label[0][0:batch.label[0].shape[0]
                                       - pad].asnumpy())
        if not outputs:
            return [] if not return_data else ([], None, None)
        n_out = len(outputs[0])
        merged = [np.concatenate([o[i] for o in outputs], axis=0)
                  for i in range(n_out)]
        result = merged[0] if n_out == 1 else merged
        if return_data:
            return (result, np.concatenate(data_list, axis=0),
                    np.concatenate(label_list, axis=0)
                    if label_list else None)
        return result

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        """Evaluate (reference ``model.py:697``)."""
        data = self._init_iter(X, None, is_train=False)
        self._check_arguments()
        mod = self._init_predictor(data)
        res = mod.score(data, eval_metric, num_batch=num_batch,
                        batch_end_callback=batch_end_callback, reset=reset)
        return res[0][1] if res else None

    def save(self, prefix, epoch=None):
        """Checkpoint as ``prefix-symbol.json`` + ``prefix-NNNN.params``
        (reference ``model.py:850``)."""
        if epoch is None:
            epoch = self.num_epoch
        assert epoch is not None
        save_checkpoint(prefix, epoch, self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """Reload a checkpointed estimator (reference ``model.py:873``)."""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               epoch_size=None, optimizer="sgd", initializer=None,
               eval_data=None, eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Construct + fit in one call (reference ``model.py:904``)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
