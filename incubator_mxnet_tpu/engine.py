"""Execution-engine facade.

The reference's dependency engine (``src/engine/threaded_engine*.cc``) exists
because CUDA streams need explicit dataflow ordering across host threads.  On
TPU, JAX's asynchronous dispatch + XLA give the same dataflow-async execution
model natively (SURVEY.md §7 design mapping), so this module is a *thin*
facade that preserves the reference's observable semantics:

- ``MXNET_ENGINE_TYPE=NaiveEngine`` (or ``TP_ENGINE_TYPE=naive``): every op
  blocks until complete — the race-free debugging oracle the reference
  documents at ``src/engine/threaded_engine.h:347-355``.
- ``wait_to_read`` / ``waitall``: ``jax.block_until_ready`` fences, matching
  ``Engine::WaitForVar`` / ``WaitForAll`` (``include/mxnet/engine.h:161-170``).
- a per-op profiler hook (mirrors ``ExecuteOprBlock``'s ``OprExecStat``
  capture, ``src/engine/threaded_engine.h:312-361``).
"""
from __future__ import annotations

import time
from typing import Any, Callable, List, Optional

from . import telemetry
from .base import get_env

__all__ = ["Engine", "engine", "naive_mode", "waitall"]


class Engine:
    """Singleton op-dispatch facade (``Engine::Get()`` analog)."""

    _instance: Optional["Engine"] = None

    def __init__(self):
        etype = (get_env("ENGINE_TYPE", "ThreadedEnginePerDevice") or "").lower()
        self.naive = etype in ("naiveengine", "naive")
        self._profile_hooks: List[Callable[[str, float, float], None]] = []
        # bounded window of recently dispatched results so WaitForAll can
        # fence on (and surface async errors from) in-flight computations
        from collections import deque

        self._inflight = deque(maxlen=int(get_env("ENGINE_INFLIGHT_WINDOW",
                                                  256, int)))

    @classmethod
    def get(cls) -> "Engine":
        if cls._instance is None:
            cls._instance = Engine()
        return cls._instance

    # -- dispatch ----------------------------------------------------------
    def push(self, fn: Callable[[], Any], name: str = "op") -> Any:
        """Run an op.  JAX already dispatches asynchronously; in naive mode we
        additionally fence so errors surface at the faulting op."""
        telemetry.counter("engine_dispatch_total").inc()
        if self._profile_hooks:
            t0 = time.perf_counter()
            out = fn()
            if self.naive:
                telemetry.counter("engine_naive_fence_total").inc()
                out = _block(out)
            t1 = time.perf_counter()
            for hook in self._profile_hooks:
                hook(name, t0, t1)
            self._inflight.append(out)
            telemetry.gauge("engine_inflight_depth").set(
                len(self._inflight))
            return out
        out = fn()
        if self.naive:
            telemetry.counter("engine_naive_fence_total").inc()
            out = _block(out)
        else:
            self._inflight.append(out)
            if telemetry.enabled():
                telemetry.gauge("engine_inflight_depth").set(
                    len(self._inflight))
        return out

    def wait_for_var(self, data) -> None:
        telemetry.counter("engine_wait_for_var_total").inc()
        _block(data)

    def wait_for_all(self) -> None:
        """Block on recently dispatched work, surfacing any async error here
        (``Engine::WaitForAll`` contract)."""
        telemetry.counter("engine_waitall_total").inc()
        while self._inflight:
            _block(self._inflight.popleft())
        if telemetry.enabled():
            telemetry.gauge("engine_inflight_depth").set(0)

    # -- profiler hook (engine-level per-op stats) -------------------------
    def add_profile_hook(self, hook) -> None:
        self._profile_hooks.append(hook)

    def remove_profile_hook(self, hook) -> None:
        if hook in self._profile_hooks:
            self._profile_hooks.remove(hook)


def _block(out):
    import jax

    return jax.block_until_ready(out)


def engine() -> Engine:
    return Engine.get()


def naive_mode() -> bool:
    return Engine.get().naive


def waitall() -> None:
    """``mx.nd.waitall()`` — block until all queued work completes
    (``MXNDArrayWaitAll`` equivalent)."""
    Engine.get().wait_for_all()
