"""Evaluation metrics (``python/mxnet/metric.py``, 1132 LoC): registry of
EvalMetric — Accuracy, TopK, F1, Perplexity, MAE/MSE/RMSE, CrossEntropy,
NegativeLogLikelihood, Torch/Caffe (numeric pass-through), CustomMetric,
CompositeEvalMetric."""
from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence

import numpy as np

from .base import MXNetError, Registry
from .ndarray.ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np_metric", "create",
           "device_partials", "DeviceMetricAccumulator"]

_REG = Registry("metric")


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


def check_label_shapes(labels, preds, shape: bool = False):
    ln = len(labels) if not shape else labels.shape
    pn = len(preds) if not shape else preds.shape
    if ln != pn:
        raise MXNetError("label/pred count mismatch: %s vs %s" % (ln, pn))


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label_dict, pred_dict):
        if self.output_names is not None:
            preds = [pred_dict[n] for n in self.output_names]
        else:
            preds = list(pred_dict.values())
        if self.label_names is not None:
            labels = [label_dict[n] for n in self.label_names]
        else:
            labels = list(label_dict.values())
        self.update(labels, preds)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


@_REG.register(name="acc")
@_REG.register(name="accuracy")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            # reference condition (metric.py:391): argmax only when the
            # prediction carries an extra class axis.  Same-rank shape
            # mismatches fall through to check_label_shapes below and
            # raise instead of being silently argmaxed into nonsense.
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(np.int32).reshape(-1)
            label = label.astype(np.int32).reshape(-1)
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(label)


@_REG.register(name="top_k_accuracy")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.top_k = top_k
        assert top_k > 1, "use Accuracy for top_k=1"
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).astype(np.int32)
            assert pred.ndim == 2
            idx = np.argsort(pred, axis=1)
            num = pred.shape[0]
            for j in range(min(self.top_k, pred.shape[1])):
                self.sum_metric += (
                    idx[:, pred.shape[1] - 1 - j].flat ==
                    label.flat).sum()
            self.num_inst += num


@_REG.register(name="f1")
class F1(EvalMetric):
    def __init__(self, name="f1", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).astype(np.int32)
            pred_label = pred.argmax(axis=1)
            if len(np.unique(label)) > 2:
                raise MXNetError("F1 supports binary classification only")
            tp = ((pred_label == 1) & (label == 1)).sum()
            fp = ((pred_label == 1) & (label == 0)).sum()
            fn = ((pred_label == 0) & (label == 1)).sum()
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            f1 = (2 * precision * recall / (precision + recall)
                  if precision + recall > 0 else 0.0)
            self.sum_metric += f1
            self.num_inst += 1


@_REG.register(name="perplexity")
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        loss, num = 0.0, 0
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(-1).astype(np.int32)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= np.sum(np.log(np.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@_REG.register(name="mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += np.abs(label - pred.reshape(label.shape)
                                      ).mean()
            self.num_inst += 1


@_REG.register(name="mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += ((label - pred.reshape(label.shape)) ** 2
                                ).mean()
            self.num_inst += 1


@_REG.register(name="rmse")
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            self.sum_metric += np.sqrt(
                ((label - pred.reshape(label.shape)) ** 2).mean())
            self.num_inst += 1


@_REG.register(name="ce")
@_REG.register(name="cross-entropy")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.ravel().astype(np.int32)
            assert label.shape[0] == pred.shape[0]
            prob = pred[np.arange(label.shape[0]), label]
            self.sum_metric += (-np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@_REG.register(name="nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@_REG.register(name="pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label).ravel(), _as_np(pred).ravel()
            self.sum_metric += np.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@_REG.register(name="loss")
class Loss(EvalMetric):
    """Mean of the output itself (for loss-symbol outputs)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += _as_np(pred).sum()
            self.num_inst += _as_np(pred).size


class Torch(Loss):
    def __init__(self, name="torch", **kwargs):
        super().__init__(name, **kwargs)


class Caffe(Loss):
    def __init__(self, name="caffe", **kwargs):
        super().__init__(name, **kwargs)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 **kwargs):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__("custom(%s)" % name, **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np_metric(name=None, allow_extra_outputs=False):
    """Decorator creating a CustomMetric from a numpy feval."""

    def wrapper(feval):
        return CustomMetric(feval, name=name,
                            allow_extra_outputs=allow_extra_outputs)

    return wrapper


def device_partials(metric):
    """Pure-jax per-batch partial for a supported ``EvalMetric``.

    Returns ``(fn, dtype)`` where ``fn(label, pred) -> (sum, count)``
    computes the metric's per-batch contribution ON DEVICE (traceable
    under ``jax.jit``), or ``None`` when the metric has no device twin
    (the train loop then falls back to the host per-batch update).

    ``Accuracy`` counts in int32 — argmax tie-breaking (first max) and
    integer compare-sum match the numpy path exactly, so accumulating on
    device is BIT-identical to the host metric.  Float-sum metrics
    (``Loss``, ``CrossEntropy``) accumulate in f32 on device vs float64
    on host, so their values agree only to f32 precision.

    Exact-type dispatch (``type(m) is``), not isinstance: a subclass may
    override ``update`` arbitrarily, and a silently wrong device twin is
    worse than the host fallback.
    """
    if type(metric) is Accuracy:
        axis = metric.axis

        def acc_fn(label, pred):
            import jax.numpy as jnp

            if pred.ndim > label.ndim:
                # jnp.argmax ties break to the first max, same as numpy
                pred = jnp.argmax(pred, axis=axis)
            pred = pred.astype(jnp.int32).reshape(-1)
            label = label.astype(jnp.int32).reshape(-1)
            if pred.shape != label.shape:
                raise MXNetError("label/pred count mismatch: %s vs %s"
                                 % (label.shape, pred.shape))
            return ((pred == label).sum(dtype=jnp.int32),
                    jnp.int32(label.shape[0]))

        return acc_fn, np.int32
    if type(metric) in (Loss, Torch, Caffe):
        def loss_fn(label, pred):
            import jax.numpy as jnp

            return (pred.sum().astype(jnp.float32),
                    jnp.float32(pred.size))

        return loss_fn, np.float32
    if type(metric) in (CrossEntropy, NegativeLogLikelihood):
        eps = metric.eps

        def ce_fn(label, pred):
            import jax.numpy as jnp

            lab = label.reshape(-1).astype(jnp.int32)
            prob = pred[jnp.arange(lab.shape[0]), lab]
            return ((-jnp.log(prob + eps)).sum().astype(jnp.float32),
                    jnp.float32(lab.shape[0]))

        return ce_fn, np.float32
    return None


def _partials_key(metric):
    """Hashable identity of a metric's device twin: two metrics with
    the same key trace to the same program, so the jitted accumulate
    is shared (a fresh jit per accumulator would recompile every
    ``fit``)."""
    if type(metric) is Accuracy:
        return ("acc", metric.axis)
    if type(metric) in (Loss, Torch, Caffe):
        return ("loss",)
    if type(metric) in (CrossEntropy, NegativeLogLikelihood):
        return ("ce", metric.eps)
    return None


# jitted accumulate programs shared across accumulator instances,
# keyed by _partials_key — see update()
_ACC_JIT_CACHE: dict = {}


class DeviceMetricAccumulator:
    """On-device metric accumulation: the overlapped-loop replacement
    for the per-batch ``update_metric`` host sync.

    Per batch, ONE jitted program folds the metric partial of
    ``(label, pred)`` into a donated 2-element device buffer
    ``[sum, count]`` — no host readback, so the step pipeline keeps
    running ahead.  ``drain()`` does a single readback per window/epoch
    and adds the partials into the wrapped ``EvalMetric``, turning
    O(steps) metric readbacks into O(steps / window)
    (``metric_readbacks_total`` counts them).
    """

    def __init__(self, metric: EvalMetric, spec):
        self._metric = metric
        self._fn, self._dtype = spec
        self._buf = None
        self._acc = None
        self.pending = 0

    @classmethod
    def create(cls, metric: EvalMetric):
        """Accumulator for ``metric``, or None when unsupported."""
        spec = device_partials(metric)
        if spec is None:
            return None
        return cls(metric, spec)

    @property
    def metric(self) -> EvalMetric:
        return self._metric

    def _zeros(self):
        import jax.numpy as jnp

        return jnp.zeros((2,), self._dtype)

    def update(self, labels, preds) -> None:
        """Fold one batch into the device buffer (no host sync).

        ``labels``/``preds`` are NDArrays or jax arrays, paired like
        ``EvalMetric.update``.
        """
        import jax

        if self._acc is None:
            key = _partials_key(self._metric)
            self._acc = _ACC_JIT_CACHE.get(key)
            if self._acc is None:
                fn = self._fn

                def accumulate(buf, label, pred):
                    import jax.numpy as jnp

                    s, c = fn(label, pred)
                    return buf + jnp.stack([s, c]).astype(buf.dtype)

                # donated buffer: the rebind recycles the 8-byte cell
                # instead of growing a live-buffer chain per step
                self._acc = jax.jit(accumulate, donate_argnums=(0,))
                if key is not None:
                    _ACC_JIT_CACHE[key] = self._acc
        if self._buf is None:
            self._buf = self._zeros()
        if len(labels) != len(preds):
            raise MXNetError("label/pred count mismatch: %s vs %s"
                             % (len(labels), len(preds)))
        for label, pred in zip(labels, preds):
            lab = label.data if isinstance(label, NDArray) else label
            prd = pred.data if isinstance(pred, NDArray) else pred
            self._buf = self._acc(self._buf, lab, prd)
        self.pending += 1

    def drain(self) -> EvalMetric:
        """ONE host readback: fold pending partials into the metric,
        re-zero the device buffer.  Doubles as a true execution fence
        (the buffer depends on every accumulated step's outputs)."""
        if self._buf is None or self.pending == 0:
            return self._metric
        vals = np.asarray(self._buf)
        from . import telemetry

        telemetry.counter("metric_readbacks_total").inc()
        if vals.dtype.kind in "iu":
            self._metric.sum_metric += int(vals[0])
        else:
            self._metric.sum_metric += float(vals[0])
        self._metric.num_inst += int(vals[1])
        self._buf = self._zeros()
        self.pending = 0
        return self._metric


def create(metric, **kwargs) -> EvalMetric:
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        c = CompositeEvalMetric()
        for m in metric:
            c.add(create(m, **kwargs))
        return c
    return _REG.get(metric)(**kwargs)
