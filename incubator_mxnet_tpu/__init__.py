"""incubator_mxnet_tpu — a TPU-native deep-learning framework with the
capabilities of Apache MXNet (incubating) v0.11.

Built from scratch on jax/XLA/pallas/pjit: the reference
(SmartAILM/incubator-mxnet) defines WHAT — the API surface, semantics and
test contract documented in SURVEY.md — while the architecture here is
TPU-first: XLA owns kernels/fusion/memory, ``jax.sharding`` + collectives own
distribution, and the runtime layers (engine, kvstore, io) are thin native
facades over them.

Usage mirrors the reference::

    import incubator_mxnet_tpu as mx
    a = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=10)
"""
from __future__ import annotations

from .libinfo import __version__  # single source of the version

# Collective-worker rendezvous must run BEFORE anything touches the XLA
# backend (jax.distributed.initialize contract).  A process spawned by
# ``tools/launch.py`` without PS servers joins the jax.distributed cluster
# here, at import — mirroring the reference where ps-lite's Postoffice
# rendezvouses during library init (SURVEY.md §3.5).
def _maybe_init_distributed():
    import os

    if os.environ.get("DMLC_ROLE", "worker") != "worker":
        return
    if int(os.environ.get("DMLC_NUM_SERVER", "0")) > 0:
        return  # PS transport owns rendezvous; jax stays single-process
    from .base import get_env

    # bare name (tools/launch.py contract) or the TP_/MXNET_ prefixes
    coord = os.environ.get("KVSTORE_COORDINATOR") \
        or get_env("KVSTORE_COORDINATOR")
    n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    if not coord or n <= 1:
        return
    import jax

    rank = int(os.environ.get("DMLC_WORKER_ID",
                              os.environ.get("TP_PROCESS_ID", "0")))
    port = os.environ.get("JAX_COORD_PORT", "9876")
    try:
        jax.distributed.initialize(
            coordinator_address="%s:%s" % (coord, port),
            num_processes=n, process_id=rank)
    except RuntimeError:
        # backend already up (user imported jax and computed first) or
        # double-init; DistKVStore._init_distributed retries with a clear
        # error path
        pass


_maybe_init_distributed()
del _maybe_init_distributed

from . import base
from .base import MXNetError

# TSan-lite (docs/static_analysis.md): TP_LOCK_CHECK=1 arms the runtime
# lock-order checker BEFORE any module creates its locks, so every
# threading primitive in the process is order-checked from birth.
if base.get_env("LOCK_CHECK", False, bool):
    from .analysis.lock_checker import install_runtime_checker

    install_runtime_checker()
    del install_runtime_checker
# TP_RACE_CHECK=1 arms the Eraser-mode lockset tracker over the
# @race_audit classes (implies the lock checker: it reads the
# per-thread held stacks)
if base.get_env("RACE_CHECK", False, bool):
    from .analysis.race_checker import install_race_checker

    install_race_checker()
    del install_race_checker
from .context import Context, cpu, tpu, gpu, cpu_pinned, current_context, \
    num_tpus, num_gpus
from . import engine
from . import random
from . import autograd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray

# Stage-gated imports: these grow as layers land (SURVEY.md §7 ordering).
_OPTIONAL = [
    ("symbol", ("sym",)), ("executor", ()), ("initializer", ()),
    ("optimizer", ()), ("lr_scheduler", ()), ("metric", ()), ("io", ()),
    ("recordio", ()), ("kvstore", ("kv",)), ("callback", ()),
    ("monitor", ()), ("module", ("mod",)), ("name", ()), ("attribute", ()),
    ("registry", ()), ("profiler", ()), ("telemetry", ()),
    ("visualization", ("viz",)),
    ("test_utils", ()), ("parallel", ()), ("models", ()), ("gluon", ()),
    ("rnn", ()), ("image", ()), ("operator", ()), ("rtc", ()),
    ("contrib", ()), ("log", ()), ("libinfo", ()), ("torch", ()),
    ("predictor", ()), ("serving", ()), ("quant", ()),
    ("resilience", ()),
]

import importlib as _importlib
import sys as _sys

for _name, _aliases in _OPTIONAL:
    try:
        _m = _importlib.import_module("." + _name, __name__)
    except ModuleNotFoundError as _e:
        # only tolerate the module itself not existing yet; real import bugs
        # inside an existing module must surface
        if _e.name and _e.name.endswith("." + _name):
            continue
        raise
    globals()[_name] = _m
    for _a in _aliases:
        globals()[_a] = _m
        _sys.modules[__name__ + "." + _a] = _m

if "symbol" in globals():
    Symbol = symbol.Symbol  # noqa: F821
if "initializer" in globals():
    init = initializer.init  # noqa: F821  (mx.init.Xavier() style)
if "attribute" in globals():
    AttrScope = attribute.AttrScope  # noqa: F821
if "optimizer" in globals():
    Optimizer = optimizer.Optimizer  # noqa: F821

waitall = nd.waitall

# Server-role bootstrap: a process launched with DMLC_ROLE=server or
# =scheduler parks in the serving loop at import and exits when the job
# finishes — the reference's ``_init_kvstore_server_module`` contract
# (python/mxnet/kvstore_server.py:80-85).
if __import__("os").environ.get("DMLC_ROLE") in ("server", "scheduler"):
    from . import kvstore_server as _kvstore_server

    if _kvstore_server.init_server_module():
        _sys.exit(0)
