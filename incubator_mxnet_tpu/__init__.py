"""incubator_mxnet_tpu — a TPU-native deep-learning framework with the
capabilities of Apache MXNet (incubating) v0.11.

Built from scratch on jax/XLA/pallas/pjit: the reference
(SmartAILM/incubator-mxnet) defines WHAT — the API surface, semantics and
test contract documented in SURVEY.md — while the architecture here is
TPU-first: XLA owns kernels/fusion/memory, ``jax.sharding`` + collectives own
distribution, and the runtime layers (engine, kvstore, io) are thin native
facades over them.

Usage mirrors the reference::

    import incubator_mxnet_tpu as mx
    a = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=10)
"""
from __future__ import annotations

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from .context import Context, cpu, tpu, gpu, cpu_pinned, current_context, \
    num_tpus, num_gpus
from . import engine
from . import random
from . import autograd
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray

# Stage-gated imports: these grow as layers land (SURVEY.md §7 ordering).
_OPTIONAL = [
    ("symbol", ("sym",)), ("executor", ()), ("initializer", ()),
    ("optimizer", ()), ("lr_scheduler", ()), ("metric", ()), ("io", ()),
    ("recordio", ()), ("kvstore", ("kv",)), ("callback", ()),
    ("monitor", ()), ("module", ("mod",)), ("name", ()), ("attribute", ()),
    ("registry", ()), ("profiler", ()), ("visualization", ("viz",)),
    ("test_utils", ()), ("parallel", ()), ("models", ()), ("gluon", ()),
    ("rnn", ()), ("image", ()), ("operator", ()), ("rtc", ()),
    ("contrib", ()),
]

import importlib as _importlib
import sys as _sys

for _name, _aliases in _OPTIONAL:
    try:
        _m = _importlib.import_module("." + _name, __name__)
    except ModuleNotFoundError as _e:
        # only tolerate the module itself not existing yet; real import bugs
        # inside an existing module must surface
        if _e.name and _e.name.endswith("." + _name):
            continue
        raise
    globals()[_name] = _m
    for _a in _aliases:
        globals()[_a] = _m
        _sys.modules[__name__ + "." + _a] = _m

if "symbol" in globals():
    Symbol = symbol.Symbol  # noqa: F821
if "initializer" in globals():
    init = initializer.init  # noqa: F821  (mx.init.Xavier() style)
if "attribute" in globals():
    AttrScope = attribute.AttrScope  # noqa: F821
if "optimizer" in globals():
    Optimizer = optimizer.Optimizer  # noqa: F821

waitall = nd.waitall
