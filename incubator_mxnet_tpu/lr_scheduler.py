"""Learning-rate schedulers (``python/mxnet/lr_scheduler.py``):
FactorScheduler / MultiFactorScheduler (+ Poly, used by examples)."""
from __future__ import annotations

import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    def __init__(self, step: int, factor: float = 1.0, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update: int) -> float:
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("lr hit stop_factor_lr %.3e", self.base_lr)
            else:
                logging.info("update %d: lr -> %.3e", num_update,
                             self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    def __init__(self, step, factor: float = 1.0):
        super().__init__()
        assert isinstance(step, list) and len(step) >= 1
        for i, s in enumerate(step):
            if i != 0 and step[i] <= step[i - 1]:
                raise ValueError("step must be increasing")
            if s < 1:
                raise ValueError("step must be >= 1")
        self.step = step
        self.cur_step_ind = 0
        self.factor = factor
        self.count = 0

    def __call__(self, num_update: int) -> float:
        while self.cur_step_ind <= len(self.step) - 1:
            if num_update > self.step[self.cur_step_ind]:
                self.count = self.step[self.cur_step_ind]
                self.cur_step_ind += 1
                self.base_lr *= self.factor
                logging.info("update %d: lr -> %.3e", num_update,
                             self.base_lr)
            else:
                return self.base_lr
        return self.base_lr


class PolyScheduler(LRScheduler):
    def __init__(self, max_update: int, power: float = 2.0, base_lr=0.01):
        super().__init__(base_lr)
        self.max_update = max_update
        self.power = power
        self.base_lr_orig = base_lr

    def __call__(self, num_update: int) -> float:
        if num_update <= self.max_update:
            self.base_lr = self.base_lr_orig * math.pow(
                1.0 - float(num_update) / self.max_update, self.power)
        return self.base_lr
