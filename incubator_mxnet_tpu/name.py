"""Automatic symbol naming (``python/mxnet/name.py``): thread-local
``NameManager`` stack assigning ``conv0``, ``conv1``, … when the user gives no
explicit name, and ``Prefix`` variant for scoped prefixes."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]

_state = threading.local()


class NameManager:
    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint: str):
        if name:
            return name
        hint = hint.lower()
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if not hasattr(_state, "stack"):
            _state.stack = [NameManager()]
        self._old = _state.stack[-1]
        _state.stack.append(self)
        return self

    def __exit__(self, *exc):
        _state.stack.pop()


class Prefix(NameManager):
    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current() -> NameManager:
    if not hasattr(_state, "stack"):
        _state.stack = [NameManager()]
    return _state.stack[-1]
