"""Deterministic fault injection for resilience testing.

The reference stack inherited ps-lite's chaos knobs (``PS_DROP_MSG``
randomly drops Van messages so recovery paths get exercised); this is
the TPU-port equivalent, widened to cover the whole training loop so
``tests/test_resilience.py`` and the ``tools/check.py`` resilience gate
can *prove* crash-at-any-step recovery instead of asserting it.

Spec grammar (``TP_FAULT_SPEC``, comma-separated rules)::

    action@point[=value][:prob]

    crash@step=7        raise InjectedFault at step boundary 7
    crash@save          raise inside the checkpoint writer, after the
                        payload is on disk but BEFORE the commit marker
                        (leaves a torn, uncommitted checkpoint dir)
    ps_drop@push:0.2    drop 20% of ps push RPCs (ConnectionError,
                        consumed by the client's retry/backoff path)

Points: ``step`` (fit-loop step boundary), ``save`` (checkpoint
writer), ``push``/``pull``/``init`` (ps data-plane RPCs).  Probabilistic
rules draw from one ``random.Random(TP_FAULT_SEED)`` stream (default
seed 0), so a given spec+seed fires on exactly the same RPCs every run
— determinism is what lets an A/B test hold the fault schedule fixed.
``crash`` rules fire AT MOST ONCE per injector: the process they model
only dies once, and a resumed in-process loop that replays the crash
step must not trip again.

``TP_FAULT_EXIT=1`` upgrades ``crash`` from an exception to a hard
``os._exit(43)`` — the subprocess-based kill tests use it to prove
recovery against a genuinely dead process, not a caught exception.

Every firing bumps ``faults_injected_total{action,point}`` and appends
to the injector's host-side ``log`` (tests assert determinism on it).
"""
from __future__ import annotations

import logging
import os
import random
import threading
from typing import List, Optional, Tuple

from .. import telemetry
from ..base import MXNetError, get_env

__all__ = ["InjectedFault", "configure", "reset", "inject", "active",
           "injector"]


class InjectedFault(MXNetError):
    """Raised by a ``crash`` rule — stands in for the process dying."""


class _Rule:
    __slots__ = ("action", "point", "value", "prob", "fired")

    def __init__(self, action: str, point: str, value: Optional[int],
                 prob: float):
        self.action = action
        self.point = point
        self.value = value
        self.prob = prob
        self.fired = False

    def __repr__(self):
        return "_Rule(%s@%s=%s:%s)" % (self.action, self.point,
                                       self.value, self.prob)


_ACTIONS = ("crash", "ps_drop")


def _parse(spec: str) -> List[_Rule]:
    rules = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise MXNetError("bad fault rule %r: expected "
                             "action@point[=value][:prob]" % part)
        action, rest = part.split("@", 1)
        action = action.strip()
        if action not in _ACTIONS:
            raise MXNetError("bad fault rule %r: unknown action %r "
                             "(known: %s)" % (part, action,
                                              ", ".join(_ACTIONS)))
        prob = 1.0
        if ":" in rest:
            rest, p = rest.rsplit(":", 1)
            try:
                prob = float(p)
            except ValueError:
                raise MXNetError("bad fault rule %r: probability %r is "
                                 "not a float" % (part, p)) from None
        value: Optional[int] = None
        if "=" in rest:
            rest, v = rest.split("=", 1)
            try:
                value = int(v)
            except ValueError:
                raise MXNetError("bad fault rule %r: value %r is not an "
                                 "int" % (part, v)) from None
        rules.append(_Rule(action, rest.strip(), value, prob))
    return rules


class Injector:
    """Parsed rule set + seeded RNG + host-side firing log."""

    def __init__(self, rules: List[_Rule], seed: int):
        self.rules = rules
        self.seed = seed
        self.rng = random.Random(seed)
        self.log: List[Tuple[str, str, Optional[int]]] = []

    def inject(self, point: str, step: Optional[int] = None) -> None:
        for rule in self.rules:
            if rule.point != point:
                continue
            if rule.value is not None and step != rule.value:
                continue
            if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                continue
            if rule.action == "crash" and rule.fired:
                continue
            rule.fired = True
            self.log.append((rule.action, point, step))
            telemetry.counter("faults_injected_total",
                              {"action": rule.action,
                               "point": point}).inc()
            if rule.action == "crash":
                msg = ("injected crash at %s%s"
                       % (point, "" if step is None else "=%d" % step))
                if get_env("FAULT_EXIT", 0, int):
                    logging.error("resilience: %s — hard exit", msg)
                    os._exit(43)
                raise InjectedFault(msg)
            if rule.action == "ps_drop":
                raise ConnectionError(
                    "injected ps drop at %s (seed=%d)" % (point, self.seed))


_LOCK = threading.Lock()
_INJECTOR: Optional[Injector] = None


def configure(spec: Optional[str] = None,
              seed: Optional[int] = None) -> Injector:
    """(Re)build the process-wide injector.  ``None`` arguments read the
    ``TP_FAULT_SPEC`` / ``TP_FAULT_SEED`` env knobs."""
    global _INJECTOR
    with _LOCK:
        if spec is None:
            spec = get_env("FAULT_SPEC", "", str) or ""
        if seed is None:
            seed = int(get_env("FAULT_SEED", 0, int))
        _INJECTOR = Injector(_parse(spec), seed)
        return _INJECTOR


def reset() -> None:
    """Drop the injector; the next ``inject`` re-reads the env."""
    global _INJECTOR
    with _LOCK:
        _INJECTOR = None


def injector() -> Injector:
    """The live injector (env-configured on first use)."""
    inj = _INJECTOR
    if inj is None:
        inj = configure()
    return inj


def active() -> bool:
    return bool(injector().rules)


def inject(point: str, step: Optional[int] = None) -> None:
    """Hook point — a no-op unless a configured rule matches ``point``."""
    inj = _INJECTOR
    if inj is None:
        inj = configure()
    if inj.rules:
        inj.inject(point, step)
