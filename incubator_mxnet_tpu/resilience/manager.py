"""Async checkpoint manager + preemption-aware resume.

The failure story ROADMAP item 1 asks for, built from the pieces the
repo already has: ``parallel/checkpoint`` (orbax sharded save/restore
with ZeRO reshard-on-restore), ``overlap.InflightRing`` (the only true
execution fence on this platform), and ``telemetry``.

Design (the Check-N-Run NSDI'22 shape — snapshot synchronously, persist
asynchronously):

1. **Snapshot on the train thread.**  The fused/pipeline steps DONATE
   their state buffers to the next step call, so a background thread
   holding live ``jax.Array`` refs would read recycled memory.  The
   manager first fences in-flight work (``overlap.drain_target`` —
   ``step.sync()`` / ring drain), then ``jax.device_get``s the state
   dict.  That host copy is immutable; only it crosses the thread
   boundary.
2. **Write + commit marker in the background.**  The writer thread
   persists the snapshot into ``<dir>/step_XXXXXXXX/`` and then — and
   only then — creates the ``COMMIT`` marker (JSON metadata: step,
   target kind, caller extras such as the data-iter cursor) via
   fsync + atomic rename.  A crash mid-write leaves a directory without
   a marker, which restore skips; readers never see a torn checkpoint.
3. **Keep-last-N GC** runs after each commit, deleting older committed
   steps beyond ``keep_last`` and failed (uncommitted) attempts older
   than the newest commit.
4. **Restore falls back**: ``restore_latest`` walks committed steps
   newest-first and drops to the previous one when a directory turns
   out corrupt.  Step targets restore through the resharding orbax
   path, so a checkpoint written with ZeRO off resumes onto a ZeRO-on
   step (and vice versa).
5. **Fail fast**: a writer-thread exception is captured and re-raised
   on the next ``step_end``/``save``/``wait`` — a run must not train
   for hours believing it is protected while saves silently fail.

Preemption: ``install_preemption_handler`` arms SIGTERM/SIGINT; the
first signal requests a final synchronous checkpoint at the next step
boundary (``step_end`` returns True → the loop exits cleanly), a second
signal falls through to the previous handler.

Targets: fused/pipeline train steps (anything exposing
``opt_states``/``num_update``, saved via orbax) and ``Module``
(host params + updater state + optimizer update counters, saved as
``module.npz`` + ``optimizer.bin``).

Env knobs: ``TP_CKPT_DIR``/``TP_CKPT_EVERY``/``TP_CKPT_KEEP``/
``TP_CKPT_ASYNC`` (see ``from_env``); docs/fault_tolerance.md has the
full contract.
"""
from __future__ import annotations

import json
import logging
import os
import pickle
import queue
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry, tracing
from ..analysis.race_checker import race_audit
from ..base import MXNetError, get_env
from ..overlap import drain_target
from . import faults

__all__ = ["CheckpointManager", "install_preemption_handler",
           "preemption_requested", "request_preemption",
           "clear_preemption"]

_STEP_FMT = "step_%08d"
_COMMIT = "COMMIT"


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------

_PREEMPT = threading.Event()
_PREV_HANDLERS: Dict[int, Any] = {}


def preemption_requested() -> bool:
    """True once a SIGTERM/SIGINT (or ``request_preemption``) arrived."""
    return _PREEMPT.is_set()


def request_preemption() -> None:
    """Programmatic preemption (what the signal handler calls)."""
    _PREEMPT.set()
    telemetry.counter("preemptions_total").inc()


def clear_preemption() -> None:
    _PREEMPT.clear()


def _on_signal(signum, frame):
    import signal as _signal

    # one-shot: restore the previous handler so a SECOND signal acts
    # normally (default SIGINT: KeyboardInterrupt; SIGTERM: kill) — an
    # operator who really wants the process gone is not locked out
    prev = _PREV_HANDLERS.pop(signum, None)
    if prev is not None:
        try:
            _signal.signal(signum, prev)
        except (ValueError, OSError):
            pass
    logging.warning("resilience: signal %d received — final checkpoint "
                    "at the next step boundary, then clean exit", signum)
    request_preemption()
    _flush_observability()


def _flush_observability() -> None:
    """Best-effort flush of the metrics/trace tail — the exit-time
    dumps never run when a SIGTERM'd process is killed before atexit,
    so preemption flushes eagerly (docs/tracing.md)."""
    try:
        telemetry.flush()
    except Exception:  # noqa: BLE001 — flush must never mask shutdown
        pass
    try:
        tracing.flush()
    except Exception:  # noqa: BLE001
        pass


def install_preemption_handler(signals: Optional[Tuple[int, ...]] = None
                               ) -> bool:
    """Arm the preemption flag on SIGTERM/SIGINT.  Idempotent; signal
    handlers can only be installed from the main thread — returns False
    (and stays un-armed) anywhere else."""
    import signal as _signal

    if signals is None:
        signals = (_signal.SIGTERM, _signal.SIGINT)
    try:
        for s in signals:
            if s in _PREV_HANDLERS:
                continue
            _PREV_HANDLERS[s] = _signal.signal(s, _on_signal)
    except ValueError:
        # not the main thread
        return False
    return True


# ---------------------------------------------------------------------------
# target state snapshot/restore (train steps + Module)
# ---------------------------------------------------------------------------


def _is_step_target(target) -> bool:
    return hasattr(target, "opt_states") and hasattr(target, "num_update")


def _module_state(target) -> Dict[str, Any]:
    arg_p, aux_p = target.get_params()  # syncs host copies from devices
    arrays = {("arg:%s" % k): np.asarray(v.asnumpy())
              for k, v in arg_p.items()}
    arrays.update({("aux:%s" % k): np.asarray(v.asnumpy())
                   for k, v in aux_p.items()})
    opt = None
    updater = getattr(target, "_updater", None)
    optimizer = getattr(target, "_optimizer", None)
    if updater is not None:
        # Updater.states alone is not enough for bit-exact resume: Adam's
        # bias correction reads the per-index update counters off the
        # Optimizer instance, so they ride along
        opt = {
            "updater": updater.get_states(),
            "num_update": int(getattr(optimizer, "num_update", 0)),
            "index_update_count": dict(
                getattr(optimizer, "_index_update_count", {})),
        }
    return {"arrays": arrays, "optimizer": opt}


def _module_restore(target, path: str) -> None:
    data = np.load(os.path.join(path, "module.npz"))
    arg_params, aux_params = {}, {}
    for key in data.files:
        kind, name = key.split(":", 1)
        (arg_params if kind == "arg" else aux_params)[name] = data[key]
    target.set_params(arg_params, aux_params, force_init=True)
    opt_file = os.path.join(path, "optimizer.bin")
    if os.path.exists(opt_file):
        with open(opt_file, "rb") as f:
            opt = pickle.loads(f.read())
        updater = getattr(target, "_updater", None)
        if updater is None:
            raise MXNetError("checkpoint carries optimizer state but the "
                             "target Module has no local updater")
        updater.set_states(opt["updater"])
        optimizer = getattr(target, "_optimizer", None)
        if optimizer is not None:
            optimizer.num_update = int(opt["num_update"])
            optimizer._index_update_count = dict(opt["index_update_count"])


def _tree_bytes(state) -> int:
    total = 0
    stack = [state]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
        elif isinstance(node, bytes):
            total += len(node)
        elif hasattr(node, "nbytes"):
            total += int(node.nbytes)
    return total


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------


# the host-side mirrors are written under _mirror_lock but read
# lock-free by benches/tests (monitoring-only), so they sit outside
# lockset refinement
@race_audit(exempt=("saves_completed", "gc_removed",
                    "last_save_seconds", "last_restore_seconds"))
class CheckpointManager:
    """Periodic (optionally async) checkpointing with commit markers,
    keep-last-N GC, corrupt-checkpoint fallback, and preemption saves.

    Parameters
    ----------
    directory : checkpoint root; one ``step_XXXXXXXX/`` child per save
    every_n_steps : cadence for :meth:`maybe_save`/:meth:`step_end`
        (0 disables periodic saves; explicit :meth:`save` still works)
    keep_last : committed checkpoints retained by GC (0 = keep all)
    async_save : hand the host snapshot to a background writer thread
        (the train loop only pays fence + D2H); False writes in the
        caller's thread with orbax streaming straight from device
    """

    def __init__(self, directory: str, every_n_steps: int = 100,
                 keep_last: int = 3, async_save: bool = True):
        self.directory = os.path.abspath(directory)
        self.every_n_steps = int(every_n_steps)
        self.keep_last = int(keep_last)
        self.async_save = bool(async_save)
        os.makedirs(self.directory, exist_ok=True)
        # host-side mirrors (benches/tests read these without telemetry);
        # written by the async writer thread AND by sync-mode callers, so
        # every access goes through _mirror_lock
        self._mirror_lock = threading.Lock()
        self.saves_completed = 0
        self.gc_removed = 0
        self.last_save_seconds = 0.0
        self.last_restore_seconds = 0.0
        self._writer_exc: Optional[BaseException] = None
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        if self.async_save:
            self._queue = queue.Queue()
            self._thread = threading.Thread(target=self._writer_loop,
                                            name="ckpt-writer", daemon=True)
            self._thread.start()

    @classmethod
    def from_env(cls) -> Optional["CheckpointManager"]:
        """Build from ``TP_CKPT_DIR``/``TP_CKPT_EVERY``/``TP_CKPT_KEEP``/
        ``TP_CKPT_ASYNC``; None when no directory is configured."""
        directory = get_env("CKPT_DIR", "", str)
        if not directory:
            return None
        return cls(directory,
                   every_n_steps=int(get_env("CKPT_EVERY", 100, int)),
                   keep_last=int(get_env("CKPT_KEEP", 3, int)),
                   async_save=bool(int(get_env("CKPT_ASYNC", 1, int))))

    # ------------------------------------------------------------- inventory
    def _step_dirs(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.startswith("step_"):
                continue
            try:
                step = int(name.split("_", 1)[1])
            except ValueError:
                continue
            out.append((step, os.path.join(self.directory, name)))
        out.sort()
        return out

    def committed_steps(self) -> List[int]:
        """Steps with a COMMIT marker, ascending."""
        return [s for s, p in self._step_dirs()
                if os.path.exists(os.path.join(p, _COMMIT))]

    def latest_step(self) -> Optional[int]:
        """Newest committed step, or None."""
        steps = self.committed_steps()
        return steps[-1] if steps else None

    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, _STEP_FMT % step)

    def metadata(self, step: int) -> Dict[str, Any]:
        with open(os.path.join(self.step_path(step), _COMMIT)) as f:
            return json.load(f)

    # ----------------------------------------------------------------- save
    def save(self, target, step: int, extra: Optional[Dict] = None,
             sync: bool = False) -> None:
        """Checkpoint ``target`` as ``step``.  Async managers enqueue the
        host snapshot; ``sync=True`` additionally waits for the write to
        commit (the preemption final save)."""
        self._check_writer()
        kind, state = self._snapshot(target)
        meta = {"step": int(step), "kind": kind, "extra": dict(extra or {})}
        if self._queue is None:
            self._write(int(step), kind, state, meta)
            return
        self._queue.put((int(step), kind, state, meta))
        telemetry.gauge("ckpt_async_queue_depth").set(self._queue.qsize())
        if sync:
            self._queue.join()
            self._check_writer()

    def maybe_save(self, target, step: int,
                   extra: Optional[Dict] = None) -> bool:
        """Periodic save when ``step`` hits the cadence."""
        if self.every_n_steps <= 0 or step <= 0 \
                or step % self.every_n_steps:
            return False
        self.save(target, step, extra=extra)
        return True

    def step_end(self, target, step: int,
                 extra: Optional[Dict] = None) -> bool:
        """The per-step hook for training loops: re-raises a failed async
        writer, honors a pending preemption request with a final
        synchronous save (returns True → stop training), otherwise runs
        the periodic :meth:`maybe_save` (returns False)."""
        self._check_writer()
        if preemption_requested():
            self.save(target, step, extra=extra, sync=True)
            logging.warning("resilience: preemption checkpoint committed "
                            "at step %d; stopping cleanly", step)
            return True
        self.maybe_save(target, step, extra=extra)
        return False

    def wait(self) -> None:
        """Block until every queued save committed; re-raises a writer
        failure."""
        if self._queue is not None:
            self._queue.join()
        self._check_writer()

    def close(self) -> None:
        """Drain queued saves and stop the writer thread.  Cleanup-safe:
        does NOT re-raise a captured writer failure (``wait``/
        ``step_end`` do)."""
        if self._queue is not None and self._thread is not None \
                and self._thread.is_alive():
            self._queue.join()
            self._queue.put(None)
            self._thread.join(timeout=60)
        # a closing manager is a run winding down: persist the
        # observability tail now, not at interpreter exit
        _flush_observability()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------------------- restore
    def restore_latest(self, target) -> Optional[Dict[str, Any]]:
        """Restore the newest committed checkpoint onto ``target``,
        falling back to older commits when a directory is corrupt.
        Returns the commit metadata (``{"step", "kind", "extra"}``) or
        None when nothing restorable exists."""
        for step in reversed(self.committed_steps()):
            path = self.step_path(step)
            t0 = time.monotonic()
            try:
                meta = self.metadata(step)
                self._restore_into(target, path, meta)
            except Exception as exc:  # noqa: BLE001 — fall back, by design
                logging.warning(
                    "resilience: checkpoint step %d at %s unreadable (%r) "
                    "— falling back to the previous commit", step, path,
                    exc)
                telemetry.counter("ckpt_restore_failures_total").inc()
                continue
            dt = time.monotonic() - t0
            self.last_restore_seconds = dt
            telemetry.counter("restores_total").inc()
            telemetry.histogram("ckpt_restore_seconds").observe(dt)
            logging.info("resilience: resumed from checkpoint step %d "
                         "(%.3fs)", step, dt)
            return meta
        return None

    # -------------------------------------------------------------- internals
    def _check_writer(self) -> None:
        with self._mirror_lock:
            exc = self._writer_exc
        if exc is not None:
            raise exc

    def _snapshot(self, target) -> Tuple[str, Any]:
        # fence first: with TP_MAX_INFLIGHT>1 earlier steps may still be
        # dispatched-but-unexecuted against buffers a queued step donates
        drain_target(target)
        if _is_step_target(target):
            from ..parallel import checkpoint as pckpt

            state = pckpt.state_dict(target)
            if self._queue is not None:
                import jax

                # host snapshot: the async writer must never hold live
                # (donatable) device arrays across step boundaries
                state = jax.device_get(state)
            return "step", state
        if hasattr(target, "get_params"):
            return "module", _module_state(target)
        raise MXNetError("CheckpointManager: unsupported target type %r "
                         "(want a fused/pipeline train step or a Module)"
                         % type(target).__name__)

    def _restore_into(self, target, path: str, meta: Dict) -> None:
        kind = meta.get("kind", "step")
        if kind == "step":
            if not _is_step_target(target):
                raise MXNetError("checkpoint %s holds train-step state "
                                 "but the target is %r"
                                 % (path, type(target).__name__))
            from ..parallel import checkpoint as pckpt

            state = pckpt.restore_state(os.path.join(path, "state"), target)
            pckpt.load_state_dict(target, state)
            return
        _module_restore(target, path)

    def _writer_loop(self) -> None:
        assert self._queue is not None
        while True:
            job = self._queue.get()
            try:
                if job is None:
                    return
                with self._mirror_lock:
                    failed = self._writer_exc is not None
                if not failed:
                    self._write(*job)
            except BaseException as exc:  # noqa: BLE001 — reported fail-fast
                # captured, surfaced on the next step boundary; keep
                # draining so queue.join() can never hang
                with self._mirror_lock:
                    self._writer_exc = exc
                logging.error("resilience: async checkpoint writer failed "
                              "(%r) — surfacing at the next step boundary",
                              exc)
            finally:
                self._queue.task_done()
                telemetry.gauge("ckpt_async_queue_depth").set(
                    self._queue.qsize())

    def _write(self, step: int, kind: str, state, meta: Dict) -> None:
        t0 = time.monotonic()
        final = self.step_path(step)
        if os.path.exists(final):
            # leftovers of a crashed attempt at this very step
            shutil.rmtree(final)
        os.makedirs(final, exist_ok=True)
        if kind == "step":
            from ..parallel import checkpoint as pckpt

            pckpt.save_state(os.path.join(final, "state"), state)
        else:
            np.savez(os.path.join(final, "module.npz"), **state["arrays"])
            if state["optimizer"] is not None:
                with open(os.path.join(final, "optimizer.bin"), "wb") as f:
                    f.write(pickle.dumps(state["optimizer"]))
        # fault hook sits between payload and marker: an injected crash
        # here leaves exactly the torn state a real mid-save death would
        faults.inject("save", step=step)
        tmp = os.path.join(final, _COMMIT + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(final, _COMMIT))
        dt = time.monotonic() - t0
        tctx = tracing.train_context()
        if tctx is not None:
            # async writes land on whichever step is CURRENT when the
            # write commits — honest overlap attribution: the span
            # shows checkpoint I/O concurrent with that step's compute
            tracing.record(tctx, "train.checkpoint", t0, t0 + dt,
                           {"step": int(step),
                            "mode": "async" if self._queue is not None
                            else "sync"})
        with self._mirror_lock:
            self.saves_completed += 1
            self.last_save_seconds = dt
        telemetry.counter("ckpt_saves_total",
                          {"mode": "async" if self._queue is not None
                           else "sync"}).inc()
        telemetry.histogram("ckpt_save_seconds").observe(dt)
        telemetry.counter("ckpt_bytes").inc(_tree_bytes(state))
        self._gc()

    def _gc(self) -> None:
        steps = self.committed_steps()
        victims = steps[:-self.keep_last] if self.keep_last > 0 else []
        for step in victims:
            shutil.rmtree(self.step_path(step), ignore_errors=True)
            with self._mirror_lock:
                self.gc_removed += 1
            telemetry.counter("ckpt_gc_total").inc()
        if not steps:
            return
        newest = steps[-1]
        for step, path in self._step_dirs():
            # failed attempts: older than the newest commit, no marker
            if step < newest and \
                    not os.path.exists(os.path.join(path, _COMMIT)):
                shutil.rmtree(path, ignore_errors=True)
                with self._mirror_lock:
                    self.gc_removed += 1
                telemetry.counter("ckpt_gc_total").inc()
