"""Fault tolerance: async checkpointing, preemption-aware resume, and
deterministic fault injection.

Three pillars (docs/fault_tolerance.md):

- :class:`CheckpointManager` — periodic async checkpoints with atomic
  commit markers, keep-last-N GC, and corrupt-checkpoint fallback;
- preemption handling — SIGTERM/SIGINT request a final synchronous
  checkpoint at the next step boundary, and ``Module.fit`` auto-resumes
  from ``restore_latest()``;
- :mod:`.faults` — the env-driven (``TP_FAULT_SPEC``) deterministic
  fault injector tests use to *prove* crash-at-any-step recovery.
"""
from . import faults
from .faults import InjectedFault
from .manager import (CheckpointManager, clear_preemption,
                      install_preemption_handler, preemption_requested,
                      request_preemption)

__all__ = ["CheckpointManager", "InjectedFault", "faults",
           "install_preemption_handler", "preemption_requested",
           "request_preemption", "clear_preemption"]
