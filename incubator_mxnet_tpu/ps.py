"""Parameter-server transport: scheduler, server, and worker client.

Reference analog: the ps-lite submodule (scheduler/server/worker roles,
ZeroMQ ``Van``, ``Postoffice`` rendezvous/barriers/dead-node watch) used by
``src/kvstore/kvstore_dist.h`` / ``kvstore_dist_server.h``.

TPU-native split: the *sync* data path of ``dist_sync`` rides XLA
collectives over DCN (see ``kvstore.py``); this module provides the pieces
collectives cannot express —

- true **async** push/pull (``dist_async``: the server applies each
  worker's gradient immediately, no cross-worker merge —
  ``kvstore_dist_server.h:154`` async branch),
- the **server role** that owns weights + updater,
- **rendezvous** (scheduler), **barriers**, **heartbeats + dead-node
  detection** (``ps::Postoffice::GetDeadNodes``, used at
  ``kvstore_dist.h:177-190``).

Transport is length-prefixed pickled messages over TCP sockets — the
stdlib stand-in for ps-lite's ZeroMQ Van.  Big arrays are range-sharded
across servers by the client (``kvstore_dist.h:302-330``).
"""
from __future__ import annotations

import os
import pickle
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry, tracing
from .base import MXNetError, get_env
from .resilience import faults as _faults

__all__ = ["Scheduler", "PSServer", "PSClient", "node_env", "DEFAULT_PORT"]

# per-verb label dicts are interned so the enabled data path never
# allocates a fresh dict per RPC
_VERB_LABELS: Dict[str, Dict[str, str]] = {}


def _verb_labels(verb: str) -> Dict[str, str]:
    lab = _VERB_LABELS.get(verb)
    if lab is None:
        lab = _VERB_LABELS[verb] = {"verb": verb}
    return lab

DEFAULT_PORT = 9091
_HDR = struct.Struct("!I")


# --------------------------------------------------------------------------
# timeouts + retry policy (docs/fault_tolerance.md knob table)
# --------------------------------------------------------------------------
# Every hang-prone wait is env-configurable so an orchestrator can trade
# patience for fast failure; the defaults match the old hardcoded values.


def _rendezvous_timeout() -> float:
    return float(get_env("PS_RENDEZVOUS_TIMEOUT", 120.0, float))


def _barrier_timeout() -> float:
    return float(get_env("PS_BARRIER_TIMEOUT", 300.0, float))


def _sync_pull_timeout() -> float:
    return float(get_env("PS_SYNC_PULL_TIMEOUT", 300.0, float))


def _deadnode_timeout() -> float:
    return float(get_env("PS_DEADNODE_TIMEOUT", 60.0, float))


def _heartbeat_interval() -> float:
    return float(get_env("PS_HEARTBEAT_INTERVAL", 5.0, float))


def _retry_backoff(attempt: int) -> float:
    """Exponential backoff with decorrelating jitter for connect/RPC
    retries (replaces the old fixed 0.2 s sleep, which synchronizes
    every retrying peer into thundering-herd waves)."""
    base = float(get_env("PS_RETRY_BASE", 0.05, float))
    cap = float(get_env("PS_RETRY_MAX", 2.0, float))
    delay = min(cap, base * (2.0 ** attempt))
    return delay * (0.5 + 0.5 * random.random())

# Bound by ``kvstore_server`` BEFORE the serve loop parks the main thread.
# Handler threads must NOT run import statements: the server blocks inside
# the package's own import (``__init__`` tail), so a handler-thread
# ``from .optimizer import ...`` would deadlock on the package import lock.
_GET_UPDATER = None
_ND_ARRAY = None


def bind_runtime() -> None:
    """Resolve the framework pieces the server role needs (called from the
    main thread while the package import lock is still reentrant there)."""
    global _GET_UPDATER, _ND_ARRAY
    from .optimizer import get_updater
    from .ndarray import array

    _GET_UPDATER = get_updater
    _ND_ARRAY = array


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def _send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class _Unpickler(pickle.Unpickler):
    """sys.modules-first class resolution.

    Server handler threads unpickle while the main thread is parked inside
    the package's import (``init_server_module``); pickle's default
    ``__import__`` of e.g. ``incubator_mxnet_tpu.optimizer`` would block on
    the parent package's import lock forever.  Every class we ship is in an
    already-initialized module, so resolve through sys.modules directly.
    """

    def find_class(self, module, name):
        import sys as _sys_mod

        mod = _sys_mod.modules.get(module)
        if mod is not None and getattr(mod, name, None) is not None:
            return getattr(mod, name)
        return super().find_class(module, name)


def _loads(payload: bytes) -> Any:
    import io

    return _Unpickler(io.BytesIO(payload)).load()


def _recv_msg(sock: socket.socket) -> Any:
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return _loads(_recv_exact(sock, n))


def _connect(addr: Tuple[str, int], timeout: float = 60.0,
             connect_retry: float = 0.0) -> socket.socket:
    """Connect with optional retry window — peers race the scheduler's
    startup (ps-lite's Van retries connects the same way), backing off
    exponentially with jitter instead of hammering a fixed cadence."""
    deadline = time.time() + connect_retry
    attempt = 0
    while True:
        try:
            return socket.create_connection(addr, timeout=timeout)
        except (ConnectionRefusedError, socket.timeout, OSError):
            remaining = deadline - time.time()
            if remaining <= 0:
                raise
            time.sleep(min(_retry_backoff(attempt), remaining))
            attempt += 1


def _rpc(addr: Tuple[str, int], obj: Any, timeout: float = 60.0,
         connect_retry: float = 0.0) -> Any:
    """One-shot request/response (control plane: register, barrier,
    heartbeat, stop)."""
    with _connect(addr, timeout, connect_retry) as sock:
        _send_msg(sock, obj)
        return _recv_msg(sock)


class _ConnPool:
    """Persistent per-peer connections for the data plane (push/pull).

    ps-lite's ZeroMQ Van keeps long-lived channels; fresh TCP connects per
    key per step would churn thousands of TIME_WAIT sockets per second.
    One socket + lock per peer; a broken socket reconnects once.
    """

    def __init__(self):
        self._conns: Dict[Tuple[str, int],
                          Tuple[socket.socket, threading.Lock]] = {}
        self._lock = threading.Lock()

    def rpc(self, addr: Tuple[str, int], obj: Any,
            timeout: float = 120.0) -> Any:
        with self._lock:
            entry = self._conns.get(addr)
        if entry is None:
            # connect OUTSIDE the pool lock: one unreachable peer must
            # not stall every other peer's push/pull for `timeout`
            # (lock-held-blocking true positive from tools/lint.py)
            sock = _connect(addr, timeout)
            with self._lock:
                entry = self._conns.setdefault(
                    addr, (sock, threading.Lock()))
            if entry[0] is not sock:  # lost the race; keep the winner
                try:
                    sock.close()
                except OSError:
                    pass
        sock, lk = entry
        with lk:
            try:
                _send_msg(sock, obj)
                return _recv_msg(sock)
            except (ConnectionError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass
                sock = _connect(addr, timeout)
                with self._lock:
                    self._conns[addr] = (sock, lk)
                _send_msg(sock, obj)
                return _recv_msg(sock)

    def close(self):
        with self._lock:
            for sock, _ in self._conns.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._conns.clear()


def node_env() -> Dict[str, str]:
    """Read the DMLC-style rendezvous env (tools/launch.py contract)."""
    return {
        "role": os.environ.get("DMLC_ROLE", "worker"),
        "scheduler_host": os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
        "scheduler_port": int(os.environ.get("DMLC_PS_ROOT_PORT",
                                             str(DEFAULT_PORT))),
        "num_workers": int(os.environ.get("DMLC_NUM_WORKER", "1")),
        "num_servers": int(os.environ.get("DMLC_NUM_SERVER", "0")),
    }


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        # serve a persistent connection: one request/reply per message
        # until the peer closes (the Van-style long-lived channel)
        while True:
            try:
                msg = _recv_msg(self.request)
            except (ConnectionError, OSError):
                return
            try:
                reply = self.server.owner._handle(msg, self)
            except Exception as exc:  # surface server-side errors
                reply = {"status": "error", "error": repr(exc)}
            if reply is not _NO_REPLY:
                try:
                    _send_msg(self.request, reply)
                except (ConnectionError, OSError):
                    return


_NO_REPLY = object()


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _Node:
    """Shared serve-loop plumbing for scheduler and server roles."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.owner = self
        self.host, self.port = self._srv.server_address
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def run(self) -> None:
        """Serve until STOP (blocks — the server-role process lives here,
        like ``KVStoreServer.run``)."""
        self.start()
        self._stopped.wait()
        self._srv.shutdown()

    def stop(self) -> None:
        self._stopped.set()
        self._srv.shutdown()
        # a stopped node must refuse NEW connections (a dead host does);
        # established handler threads drain until their peer closes
        self._srv.server_close()

    def _handle(self, msg, handler):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


class Scheduler(_Node):
    """Rendezvous + barriers + liveness (``ps::Postoffice`` analog).

    Servers REGISTER their data addresses; workers GET_NODES (blocking
    until all servers are up); every node HEARTBEATs; BARRIER releases when
    ``num_workers`` hit the same barrier id; DEAD_NODES lists nodes whose
    last heartbeat is older than a timeout (kvstore_dist.h:177-190).
    """

    def __init__(self, num_workers: int, num_servers: int,
                 host: str = "127.0.0.1", port: int = DEFAULT_PORT):
        super().__init__(host, port)
        self.num_workers = num_workers
        self.num_servers = num_servers
        self._lock = threading.Condition()
        self._servers: Dict[int, Tuple[str, int]] = {}
        self._server_gen = 0   # bumped on every (re-)registration
        self._barriers: Dict[Any, int] = {}
        self._barrier_gen: Dict[Any, int] = {}
        self._last_seen: Dict[str, float] = {}
        self._config: Dict[str, Any] = {}
        self._done = 0

    def _dead_now(self, now: float) -> List[str]:
        """Nodes with stale heartbeats (caller holds ``self._lock``)."""
        stale = _deadnode_timeout()
        return sorted(n for n, t in self._last_seen.items()
                      if now - t > stale)

    @staticmethod
    def _wait_slice(remaining: float) -> float:
        # wake often enough to notice a death well inside the stale
        # window, without spinning
        return min(remaining, max(0.05, _deadnode_timeout() / 4.0))

    def _handle(self, msg, handler):
        cmd = msg["cmd"]
        now = time.time()
        if "node" in msg:
            with self._lock:
                self._last_seen[msg["node"]] = now
        if cmd == "register_server":
            with self._lock:
                self._servers[msg["server_id"]] = tuple(msg["addr"])
                self._server_gen += 1
                # a rejoining server is alive again by definition
                self._last_seen["server%d" % msg["server_id"]] = now
                self._lock.notify_all()
            return {"status": "ok", "gen": self._server_gen}
        if cmd == "get_nodes":
            # min_gen > 0 lets a worker wait for a REPLACEMENT server
            # after observing a death (the recovery path)
            min_gen = msg.get("min_gen", 0)
            deadline = time.time() + _rendezvous_timeout()
            with self._lock:
                while (len(self._servers) < self.num_servers
                       or self._server_gen < min_gen):
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        return {"status": "error",
                                "error": "rendezvous timeout after %.0fs "
                                         "(%d/%d servers registered)"
                                         % (_rendezvous_timeout(),
                                            len(self._servers),
                                            self.num_servers)}
                    self._lock.wait(timeout=self._wait_slice(remaining))
                    dead = self._dead_now(time.time())
                    if dead:
                        # abandon instead of waiting out the full window:
                        # a dead peer cannot register
                        return {"status": "error", "dead": dead,
                                "error": "rendezvous abandoned; "
                                         "dead nodes: %s" % dead}
                return {"status": "ok", "gen": self._server_gen,
                        "servers": [self._servers[i]
                                    for i in sorted(self._servers)]}
        if cmd == "heartbeat":
            return {"status": "ok"}
        if cmd == "barrier":
            bid = msg["barrier_id"]
            with self._lock:
                gen = self._barrier_gen.setdefault(bid, 0)
                self._barriers[bid] = self._barriers.get(bid, 0) + 1
                if self._barriers[bid] >= self.num_workers:
                    self._barriers[bid] = 0
                    self._barrier_gen[bid] = gen + 1
                    self._lock.notify_all()
                else:
                    deadline = time.time() + _barrier_timeout()
                    while self._barrier_gen.get(bid, 0) == gen:
                        remaining = deadline - time.time()
                        if remaining <= 0:
                            return {"status": "error",
                                    "error": "barrier %r timeout after "
                                             "%.0fs (%d/%d arrived)"
                                             % (bid, _barrier_timeout(),
                                                self._barriers.get(bid, 0),
                                                self.num_workers)}
                        self._lock.wait(
                            timeout=self._wait_slice(remaining))
                        dead = self._dead_now(time.time())
                        if dead:
                            # a dead peer can never arrive — fail the
                            # barrier NOW and name the culprits
                            return {"status": "error", "dead": dead,
                                    "error": "barrier %r abandoned; "
                                             "dead nodes: %s"
                                             % (bid, dead)}
            return {"status": "ok"}
        if cmd == "dead_nodes":
            timeout = msg.get("timeout", 60)
            with self._lock:
                dead = [n for n, t in self._last_seen.items()
                        if now - t > timeout]
            return {"status": "ok", "dead": dead}
        if cmd == "put_config":
            # cluster-wide config (optimizer blob, sync flag) parked at the
            # scheduler so a REPLACEMENT server can fetch it at register
            # time instead of waiting for a worker to notice and resend
            with self._lock:
                self._config[msg["name"]] = msg["blob"]
            return {"status": "ok"}
        if cmd == "get_config":
            with self._lock:
                return {"status": "ok", "config": dict(self._config)}
        if cmd == "finalize":
            # workers report completion; when all have, stop the cluster
            with self._lock:
                self._done += 1
                done = self._done >= self.num_workers
                servers = list(self._servers.values())
            if done:
                for addr in servers:
                    try:
                        _rpc(addr, {"cmd": "stop"})
                    except OSError:
                        pass
                threading.Thread(target=self.stop, daemon=True).start()
            return {"status": "ok"}
        if cmd == "stop":
            threading.Thread(target=self.stop, daemon=True).start()
            return {"status": "ok"}
        return {"status": "error", "error": "unknown cmd %s" % cmd}


# ---------------------------------------------------------------------------
# server role
# ---------------------------------------------------------------------------


class PSServer(_Node):
    """Holds weight shards + runs the updater (``KVStoreDistServer``).

    - sync mode: pushes accumulate into a merge buffer; when
      ``num_workers`` pushes arrived for a key, the updater runs ONCE and
      pending pulls release (kvstore_dist_server.h:182+).  Merges are
      versioned with a per-key *round* counter (ps-lite timestamps): a
      pull from worker ``w`` waits until every round ``w`` itself pushed
      has been applied — NOT until the merge buffer drains — so a fast
      worker's round-N+1 push arriving before a slow worker's round-N
      pull cannot deadlock the slow worker;
    - async mode: each push updates immediately (``DataHandle`` async
      branch) — workers racing is the *intended* semantics.
    """

    def __init__(self, server_id: int, num_workers: int,
                 scheduler: Tuple[str, int], host: str = "127.0.0.1",
                 recovery: Optional[bool] = None):
        super().__init__(host, 0)
        self.server_id = server_id
        self.num_workers = num_workers
        self.scheduler = scheduler
        # a replacement for a dead server starts with DMLC_PS_RECOVERY=1
        # (ps::Postoffice::is_recovery analog); its store is empty until
        # workers re-seed it from their local weight copies
        self.recovery = bool(int(os.environ.get("DMLC_PS_RECOVERY", "0"))) \
            if recovery is None else recovery
        self.sync_mode = False
        self._store: Dict[Any, np.ndarray] = {}
        self._merge: Dict[Any, Tuple[np.ndarray, int]] = {}
        self._round: Dict[Any, int] = {}    # applied merges per key
        self._pushed: Dict[Any, Dict[int, int]] = {}  # key -> rank -> count
        self._updater: Optional[Callable] = None
        self._lock = threading.Condition()

    def register(self) -> None:
        if self.recovery:
            # a replacement server configures itself from the scheduler's
            # parked config BEFORE announcing its address, so no request
            # can reach an updater-less server and clobber a weight
            reply = _rpc(self.scheduler, {"cmd": "get_config"},
                         connect_retry=60.0)
            cfg = reply.get("config", {})
            if "optimizer" in cfg:
                self._updater = _GET_UPDATER(_loads(cfg["optimizer"]))
            if "sync" in cfg:
                self.sync_mode = bool(cfg["sync"])
        _rpc(self.scheduler, {"cmd": "register_server",
                              "server_id": self.server_id,
                              "addr": (self.host, self.port),
                              "node": "server%d" % self.server_id},
             connect_retry=60.0)
        # keep our liveness fresh at the scheduler; without this the
        # GetDeadNodes analog would flag healthy servers once a job
        # outlives the staleness timeout
        # tp-lint: disable=race-unlocked-shared-state -- rebound before Thread.start() publishes
        self._hb_stop = threading.Event()
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    def _heartbeat_loop(self):
        node = "server%d" % self.server_id
        while not self._hb_stop.wait(_heartbeat_interval()):
            if self._stopped.is_set():
                return
            try:
                _rpc(self.scheduler, {"cmd": "heartbeat", "node": node},
                     timeout=10.0)
            except OSError:
                telemetry.counter("ps_heartbeat_miss_total",
                                  {"role": "server"}).inc()

    def _apply(self, key, grad):
        if self._updater is not None:
            # the updater speaks NDArray (optimizer.Updater); the server
            # store is host numpy — wrap, update, write back
            weight = _ND_ARRAY(self._store[key])
            self._updater(key, _ND_ARRAY(grad), weight)
            self._store[key] = weight.asnumpy()
        else:
            self._store[key] = np.array(grad)

    def _handle(self, msg, handler):
        cmd = msg["cmd"]
        if cmd == "init":
            with self._lock:
                # recovery re-seeds are tagged by the worker: the FIRST
                # re-seed wins — later (staler) copies from workers that
                # trip on the dead server afterwards must not roll back
                # updates already applied on top of the first seed.
                # Untagged (ordinary) inits always apply, so a legitimate
                # re-init behaves identically on healthy and replaced
                # servers and shard state cannot diverge.
                if not (msg.get("reseed") and msg["key"] in self._store):
                    self._store[msg["key"]] = np.array(msg["value"],
                                                       dtype=np.float32)
            return {"status": "ok"}
        if cmd == "push":
            key, grad = msg["key"], msg["value"]
            with self._lock:
                if not self.sync_mode:
                    self._apply(key, grad)
                else:
                    rank = msg.get("rank")
                    if rank is not None:
                        ranks = self._pushed.setdefault(key, {})
                        ranks[rank] = ranks.get(rank, 0) + 1
                    buf, cnt = self._merge.get(key, (None, 0))
                    buf = grad.copy() if buf is None else buf + grad
                    cnt += 1
                    if cnt >= self.num_workers:
                        self._apply(key, buf)
                        self._merge[key] = (None, 0)
                        self._round[key] = self._round.get(key, 0) + 1
                        self._lock.notify_all()
                    else:
                        self._merge[key] = (buf, cnt)
            return {"status": "ok"}
        if cmd == "pull":
            key = msg["key"]
            rank = msg.get("rank")
            with self._lock:
                if self.sync_mode:
                    # release once every round THIS worker pushed has been
                    # applied (per-key round versioning; waiting on the
                    # merge buffer instead deadlocks across rounds when a
                    # fast worker's next push lands first)
                    def _ready():
                        if rank is None:
                            return self._merge.get(key, (None, 0))[1] == 0
                        want = self._pushed.get(key, {}).get(rank, 0)
                        return self._round.get(key, 0) >= want

                    while not _ready():
                        if not self._lock.wait(
                                timeout=_sync_pull_timeout()):
                            return {"status": "error",
                                    "error": "sync pull timeout after "
                                             "%.0fs" % _sync_pull_timeout()}
                if key not in self._store:
                    return {"status": "error",
                            "error": "key %r not initialized" % (key,)}
                return {"status": "ok", "value": self._store[key]}
        if cmd == "set_updater":
            # optimizer shipped as pickled bytes (reference sends the
            # optimizer to servers via a command, kvstore.py:set_optimizer)
            opt = _loads(msg["optimizer"])
            self._updater = _GET_UPDATER(opt)
            return {"status": "ok"}
        if cmd == "set_sync":
            self.sync_mode = bool(msg["sync"])
            return {"status": "ok"}
        if cmd == "stop":
            threading.Thread(target=self.stop, daemon=True).start()
            return {"status": "ok"}
        return {"status": "error", "error": "unknown cmd %s" % cmd}


# ---------------------------------------------------------------------------
# worker client
# ---------------------------------------------------------------------------


class PSClient:
    """Worker-side connection to the PS cluster (``ps::KVWorker``).

    Key placement: whole arrays go to ``hash(key) % num_servers``; arrays
    with more rows than ``bigarray_bound`` are range-sharded across ALL
    servers (kvstore_dist.h:302-330) so no single server owns a huge key.
    """

    def __init__(self, rank: int,
                 scheduler: Optional[Tuple[str, int]] = None,
                 bigarray_bound: Optional[int] = None,
                 recover_servers: Optional[bool] = None):
        env = node_env()
        self.rank = rank
        self.node = "worker%d" % rank
        self.scheduler = scheduler or (env["scheduler_host"],
                                       env["scheduler_port"])
        self.bigarray_bound = bigarray_bound if bigarray_bound is not None \
            else int(get_env("KVSTORE_BIGARRAY_BOUND", 1 << 19))
        # TP_PS_RECOVERY=1: on server death, wait for a replacement and
        # re-seed it instead of failing.  DMLC_PS_RECOVERY marks THIS node
        # as a rejoin (ps::Postoffice::is_recovery) → barriers are skipped.
        self.recover_servers = bool(int(
            os.environ.get("TP_PS_RECOVERY", "0"))) \
            if recover_servers is None else recover_servers
        self.is_recovery = bool(int(os.environ.get("DMLC_PS_RECOVERY",
                                                   "0")))
        reply = _rpc(self.scheduler, {"cmd": "get_nodes",
                                      "node": self.node},
                     timeout=_rendezvous_timeout() + 60.0,
                     connect_retry=60.0)
        if reply["status"] != "ok":
            # the scheduler names dead peers in the error when its
            # liveness watch abandoned the rendezvous
            raise MXNetError("rendezvous failed: %s" % reply.get("error"))
        self.servers: List[Tuple[str, int]] = [tuple(a)
                                               for a in reply["servers"]]
        if not self.servers:
            raise MXNetError("no servers registered")
        self._gen = reply.get("gen", 0)
        self._local: Dict[Any, np.ndarray] = {}  # freshest pulled weights
        self._pool = _ConnPool()
        self._hb = threading.Thread(target=self._heartbeat_loop,
                                    daemon=True)
        self._hb_stop = threading.Event()
        self._hb.start()

    # -------------------------------------------------------------- liveness
    def _heartbeat_loop(self):
        while not self._hb_stop.wait(_heartbeat_interval()):
            try:
                _rpc(self.scheduler, {"cmd": "heartbeat",
                                      "node": self.node})
            except OSError:
                telemetry.counter("ps_heartbeat_miss_total",
                                  {"role": "worker"}).inc()

    def dead_nodes(self, timeout: Optional[float] = None) -> List[str]:
        if timeout is None:
            timeout = _deadnode_timeout()
        reply = _rpc(self.scheduler, {"cmd": "dead_nodes",
                                      "timeout": timeout,
                                      "node": self.node})
        dead = reply.get("dead", [])
        if telemetry.enabled():
            telemetry.gauge("ps_dead_nodes").set(len(dead))
            if dead:
                telemetry.counter("ps_dead_node_events_total").inc()
        return dead

    # ------------------------------------------------------------- placement
    def _plan(self, key, arr: np.ndarray):
        """-> list of (server_idx, subkey, row_slice)"""
        n = len(self.servers)
        if arr.size >= self.bigarray_bound and n > 1 and arr.shape[0] >= n:
            rows = arr.shape[0]
            step = (rows + n - 1) // n
            plan = []
            for i in range(n):
                lo = i * step
                hi = min(rows, lo + step)
                if lo >= hi:
                    break
                plan.append((i, ("%s#%d" % (key, i)), slice(lo, hi)))
            return plan
        # process-stable placement (str hash is randomized per process)
        import zlib

        return [(zlib.crc32(str(key).encode()) % n, key, slice(None))]

    # --------------------------------------------------------- fault handling
    def _data_rpc(self, sidx: int, msg: Dict[str, Any]) -> Any:
        """Data-plane RPC with transient-failure retry and dead-server
        handling.

        Transient connection failures retry with exponential backoff +
        jitter (``TP_PS_RPC_RETRIES`` rounds); exhausted retries raise a
        clean ``MXNetError`` naming the unreachable server and the
        scheduler's dead-node list (the reference surfaces ps-lite van
        errors the same way).  With ``recover_servers``: wait for a
        replacement registration, re-seed it, retry.  The
        ``ps_drop@<verb>:<p>`` fault rule injects drops here, upstream of
        the retry machinery, so tests drive this exact path.
        """
        verb = msg.get("cmd", "?")
        last_exc: Optional[BaseException] = None
        tele = telemetry.enabled()
        if tele:
            lab = _verb_labels(verb)
            telemetry.counter("ps_rpc_total", lab).inc()
            v = msg.get("value")
            if isinstance(v, np.ndarray):
                telemetry.counter("ps_rpc_bytes_total", lab).inc(v.nbytes)
            t0 = time.monotonic()
        # with recovery: up to N recovery rounds — one generation bump can
        # satisfy the wait while OUR server's replacement is still
        # registering (a different server died too), so a retry may trip
        # again.  Without recovery: plain backoff retries absorb transient
        # drops instead of failing the job on the first broken socket.
        attempts = max(1, int(get_env("PS_RPC_RETRIES", 3, int)))
        for attempt in range(attempts):
            try:
                _faults.inject(verb)
                tctx = tracing.train_context()
                if tctx is None:
                    reply = self._pool.rpc(self.servers[sidx], msg)
                else:
                    # attribute the PS round-trip to the current train
                    # step's trace (docs/tracing.md)
                    tr0 = time.monotonic()
                    reply = self._pool.rpc(self.servers[sidx], msg)
                    tracing.record(tctx, "train.rpc", tr0,
                                   time.monotonic(),
                                   _verb_labels(verb))
                if tele:
                    telemetry.histogram("ps_rpc_seconds", lab).observe(
                        time.monotonic() - t0)
                    rv = reply.get("value") if isinstance(reply, dict) \
                        else None
                    if isinstance(rv, np.ndarray):
                        telemetry.counter("ps_rpc_bytes_total",
                                          lab).inc(rv.nbytes)
                return reply
            except (ConnectionError, OSError) as exc:
                last_exc = exc
                telemetry.counter("ps_rpc_retries_total").inc()
                if self.recover_servers:
                    self._recover(sidx)
                elif attempt + 1 < attempts:
                    time.sleep(_retry_backoff(attempt))
        addr = self.servers[sidx]
        dead: List[str] = []
        try:
            dead = self.dead_nodes(timeout=15)
        except OSError:
            pass
        raise MXNetError(
            "parameter server %d at %s:%d unreachable (%s); "
            "scheduler dead-node list: %s" %
            (sidx, addr[0], addr[1], last_exc, dead or "[]")) from last_exc

    def _recover(self, sidx: int) -> None:
        """Wait for a replacement server and re-seed it with our freshest
        local weight copies.

        ps-lite has no server-state recovery either (``is_recovery`` only
        skips barriers); here the worker-side weights — refreshed on every
        pull — are the surviving replica, so training resumes from at-most-
        one-round-stale values on the replaced shard.  Async mode only: a
        sync-mode merge that lost a member cannot be reconstructed, so
        sync jobs fail cleanly instead (kvstore.py gates the flag).
        """
        telemetry.counter("ps_server_recovery_total").inc()
        reply = _rpc(self.scheduler,
                     {"cmd": "get_nodes", "node": self.node,
                      "min_gen": self._gen + 1}, timeout=300.0)
        if reply["status"] != "ok":
            raise MXNetError("recovery rendezvous failed: %s"
                             % reply.get("error"))
        self._gen = reply["gen"]
        old = list(self.servers)
        self.servers = [tuple(a) for a in reply["servers"]]
        self._pool.close()
        self._pool = _ConnPool()
        # re-seed every REPLACED server (address changed), not just the
        # one we tripped over — one generation bump can cover several
        # near-simultaneous deaths.  Healthy servers keep their (fresher)
        # state: re-initing them would roll weights back.
        replaced = {i for i, a in enumerate(self.servers)
                    if i >= len(old) or tuple(old[i]) != a}
        replaced.add(sidx)
        for key, value in self._local.items():
            for si, subkey, sl in self._plan(key, value):
                if si in replaced:
                    self._pool.rpc(self.servers[si],
                                   {"cmd": "init", "key": subkey,
                                    "reseed": True,
                                    "value": value[sl]})

    # ------------------------------------------------------------------- api
    def init(self, key, value: np.ndarray) -> None:
        value = np.asarray(value, dtype=np.float32)
        if self.recover_servers:  # re-seed source; dead weight otherwise
            self._local[key] = value.copy()
        for sidx, subkey, sl in self._plan(key, value):
            self._data_rpc(sidx, {"cmd": "init", "key": subkey,
                                  "value": value[sl]})

    def push(self, key, value: np.ndarray) -> None:
        for sidx, subkey, sl in self._plan(key, value):
            reply = self._data_rpc(sidx,
                                   {"cmd": "push", "key": subkey,
                                    "rank": self.rank,
                                    "value":
                                    np.ascontiguousarray(value[sl])})
            if reply["status"] != "ok":
                raise MXNetError("push failed: %s" % reply.get("error"))

    def pull(self, key, like: np.ndarray) -> np.ndarray:
        out = np.empty_like(like)
        for sidx, subkey, sl in self._plan(key, like):
            reply = self._data_rpc(sidx, {"cmd": "pull", "key": subkey,
                                          "rank": self.rank})
            if reply["status"] != "ok":
                raise MXNetError("pull failed: %s" % reply.get("error"))
            out[sl] = reply["value"]
        if self.recover_servers:
            self._local[key] = np.array(out, dtype=np.float32, copy=True)
        return out

    def set_optimizer(self, optimizer) -> None:
        blob = pickle.dumps(optimizer)
        # parked at the scheduler too, for replacement-server bootstrap
        _rpc(self.scheduler, {"cmd": "put_config", "name": "optimizer",
                              "blob": blob, "node": self.node})
        for addr in self.servers:
            _rpc(addr, {"cmd": "set_updater", "optimizer": blob})

    def set_sync(self, sync: bool) -> None:
        _rpc(self.scheduler, {"cmd": "put_config", "name": "sync",
                              "blob": bool(sync), "node": self.node})
        for addr in self.servers:
            _rpc(addr, {"cmd": "set_sync", "sync": sync})

    def barrier(self, barrier_id="default") -> None:
        # a rejoining node skips barriers entirely so a mid-round restart
        # cannot deadlock the healthy group (ps::Postoffice::is_recovery —
        # kvstore_dist.h:57,95,196 skip the init/exit barriers)
        if self.is_recovery:
            return
        reply = _rpc(self.scheduler, {"cmd": "barrier",
                                      "barrier_id": barrier_id,
                                      "node": self.node},
                     timeout=_barrier_timeout() + 30.0)
        if reply["status"] != "ok":
            raise MXNetError("barrier failed: %s" % reply.get("error"))

    def finalize(self) -> None:
        """Barrier-before-exit + cluster shutdown vote
        (``kvstore.h:241`` barrier_before_exit)."""
        self._hb_stop.set()
        try:
            _rpc(self.scheduler, {"cmd": "finalize", "node": self.node})
        except OSError:
            pass
