"""Global PRNG state (``mx.random``).

Reference analog: per-device seeded PRNG resources
(``ResourceManagerImpl::SeedRandom``, ``src/resource.cc:145``) driven by
``mx.random.seed``.  TPU-native: a counter-based jax PRNG key chain — every
stochastic op consumes ``next_key()``, which is ``fold_in(root, counter++)``;
reseeding resets the chain, giving the reference's reproducibility contract
(same seed → same sample stream).
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "current_seed"]

_state = threading.local()
_DEFAULT_SEED = 0


def _ensure():
    if not hasattr(_state, "root"):
        import jax

        _state.seed = _DEFAULT_SEED
        _state.root = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.counter = 0


def seed(seed_state: int) -> None:
    """``mx.random.seed(n)`` — reset the global sample stream.

    Also seeds numpy's global RNG: the initializer zoo draws on the host
    through numpy, and the reference contract is that ``mx.random.seed``
    alone makes network init reproducible (``resource.cc:145`` seeds
    every device RNG the initializers use)."""
    import jax
    import numpy as np

    _state.seed = int(seed_state)
    _state.root = jax.random.PRNGKey(int(seed_state))
    _state.counter = 0
    np.random.seed(int(seed_state) % (1 << 32))


def current_seed() -> int:
    _ensure()
    return _state.seed


def next_key():
    """Next PRNG key in the stream (consumed by one stochastic op)."""
    import jax

    _ensure()
    _state.counter += 1
    return jax.random.fold_in(_state.root, _state.counter)
