"""Generic class registry factories (reference
``python/mxnet/registry.py``): build ``register``/``alias``/``create``
functions for any base class — the machinery behind
``mx.optimizer.register``-style APIs.  Storage delegates to
:class:`base.Registry` (one registry mechanism in the codebase: locked,
override-warning)."""
from __future__ import annotations

import json
from typing import Dict

from .base import MXNetError, Registry

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_REGISTRY: Dict[type, Registry] = {}


def _registry_of(base_class: type, nickname: str) -> Registry:
    reg = _REGISTRY.get(base_class)
    if reg is None:
        reg = _REGISTRY[base_class] = Registry(nickname)
    return reg


def get_register_func(base_class: type, nickname: str):
    """-> ``register(klass, name=None)`` storing subclasses by
    lower-cased name (reference ``registry.py:32``)."""
    registry = _registry_of(base_class, nickname)

    def register(klass: type, name: str = None):
        if not issubclass(klass, base_class):
            raise MXNetError("can only register subclass of %s"
                             % base_class.__name__)
        registry.register(klass, name=name or klass.__name__)
        return klass

    register.__doc__ = "Register a %s to the registry" % nickname
    return register


def get_alias_func(base_class: type, nickname: str):
    """-> ``alias(*names)`` decorator (reference ``registry.py:70``)."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass

        return reg

    return alias


def get_create_func(base_class: type, nickname: str):
    """-> ``create(name_or_instance, *args, **kwargs)`` (reference
    ``registry.py:97``); also accepts the JSON ``[name, kwargs]`` form
    produced by e.g. ``Augmenter.dumps``."""
    registry = _registry_of(base_class, nickname)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            return args[0]
        if not args or not isinstance(args[0], str):
            raise MXNetError("%s name required as the first argument"
                             % nickname)
        name, args = args[0], args[1:]
        if name.startswith("[") and not args and not kwargs:
            try:
                name, kwargs = json.loads(name)
            except (json.JSONDecodeError, ValueError, TypeError) as exc:
                raise MXNetError("invalid JSON %s spec %r: %s"
                                 % (nickname, name, exc)) from exc
        return registry.get(name)(*args, **kwargs)

    create.__doc__ = "Create a %s instance by name" % nickname
    return create
