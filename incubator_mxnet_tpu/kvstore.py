"""KVStore — the distributed communication facade.

Reference analog: ``include/mxnet/kvstore.h`` + ``src/kvstore/*`` —
``local`` (CPU-staged reduce), ``device`` (GPU P2P reduce), ``dist_sync`` /
``dist_async`` / ``dist_device_sync`` (ps-lite parameter server).

TPU-native redesign (SURVEY.md §5.8): the Init/Push/Pull/updater/Barrier API
is preserved so Module/Trainer port unchanged, but the transport is:

- ``local``: host-side tree reduce (numpy/jax on host devices);
- ``device``: XLA all-reduce across the in-process device mesh — a single
  fused ``psum`` per key group replaces CommDevice's P2P gather-scatter
  (``src/kvstore/comm.h:222``), riding ICI on a real TPU pod;
- ``dist_*``: multi-process collectives over jax.distributed (DCN between
  hosts).  The ps-lite scheduler's rendezvous role is played by the JAX
  coordination service; ``rank``/``num_workers``/``Barrier`` map to
  process_index/process_count/global sync.  Per SURVEY.md §3.5 sync-mode
  math: gradients are *summed* across workers then the updater runs once —
  exactly what a psum all-reduce computes.
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import telemetry
from .base import MXNetError, get_env
from .ndarray.ndarray import NDArray
from .ndarray import zeros as nd_zeros

__all__ = ["KVStore", "create"]


def create(name: str = "local") -> "KVStore":
    """``mx.kv.create`` — factory (``src/kvstore/kvstore.cc:34-57``)."""
    name = name.lower()
    if name not in ("local", "local_allreduce_cpu", "local_allreduce_device",
                    "device", "dist_sync", "dist_async", "dist_device_sync",
                    "dist"):
        raise MXNetError("unknown kvstore type %s" % name)
    if name.startswith("dist"):
        return DistKVStore(name)
    return KVStore(name)


class KVStore:
    """Single-process kvstore (types ``local`` and ``device``)."""

    def __init__(self, kv_type: str = "local"):
        self.type = kv_type
        self._store: Dict[Any, NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None
        # jitted psum reducers keyed by (shape, dtype, device tuple) — the
        # CommDevice merge-buffer analog, compiled once per key signature
        self._psum_cache: Dict[tuple, Callable] = {}

    # ------------------------------------------------------------------ api
    def init(self, key, value) -> None:
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %s already initialized" % k)
            self._store[k] = v[0].copy() if isinstance(v, list) else v.copy()

    def push(self, key, value, priority: int = 0) -> None:
        """Aggregate (sum) pushed values per key; run updater if set
        (``KVStoreLocal::Push``, kvstore_local.h:83)."""
        keys, values = _key_value(key, value)
        _tele = telemetry.enabled()
        for k, vlist in zip(keys, values):
            if not isinstance(vlist, list):
                vlist = [vlist]
            if _tele:
                telemetry.counter("kvstore_push_total").inc()
                telemetry.counter("kvstore_push_bytes_total").inc(
                    sum(_nd_bytes(v) for v in vlist))
            merged = self._reduce(vlist)
            if self._updater is not None:
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k]._set_data(merged.data)

    def pull(self, key, out=None, priority: int = 0) -> None:
        keys, outs = _key_value(key, out)
        _tele = telemetry.enabled()
        for k, olist in zip(keys, outs):
            if not isinstance(olist, list):
                olist = [olist]
            src = self._store[k]
            if _tele:
                telemetry.counter("kvstore_pull_total").inc()
                telemetry.counter("kvstore_pull_bytes_total").inc(
                    _nd_bytes(src) * len(olist))
            for o in olist:
                # broadcast to each destination's device
                o._set_data(_place_like(src, o))

    def row_sparse_pull(self, *a, **k):
        raise MXNetError("sparse storage is not supported")

    # ------------------------------------------------------------ reduction
    def _reduce(self, vlist: List[NDArray]) -> NDArray:
        """Sum a list of per-device gradients as ONE XLA collective.

        ``device`` semantics redesign of ``CommDevice`` (comm.h:222-343):
        instead of P2P gather-scatter onto a merge GPU, the shards are
        assembled into a global array over a 1-d mesh of the contributing
        devices and reduced by a jitted ``shard_map`` ``lax.psum`` — one
        all-reduce riding ICI, with the result replicated on every device so
        the subsequent ``pull`` broadcast is free.  Falls back to a staged
        add when shards share a device (the ``local`` type or CPU tests).
        """
        if len(vlist) == 1:
            return vlist[0]
        import jax

        devs = [next(iter(v.data.devices())) for v in vlist]
        if len(set(devs)) != len(devs):
            # duplicated devices (e.g. all on one chip): plain fused add
            acc = vlist[0].data
            dev = devs[0]
            for v in vlist[1:]:
                acc = acc + jax.device_put(v.data, dev)
            return NDArray(acc, ctx=vlist[0]._ctx)

        # canonical device ordering so a different push order of the same
        # device set reuses one compiled reducer (sum is order-invariant;
        # the psum result is replicated on every device)
        order = sorted(range(len(devs)),
                       key=lambda i: (devs[i].platform, devs[i].id))
        sdevs = [devs[i] for i in order]
        arr0 = vlist[0].data
        sig = (tuple(arr0.shape), str(arr0.dtype),
               tuple((d.platform, d.id) for d in sdevs))
        fn = self._psum_cache.get(sig)
        if fn is None:
            fn = _build_psum(sdevs, arr0.shape, arr0.dtype)
            self._psum_cache[sig] = fn
        # result shard on the push-order-first device, preserving the
        # invariant that the merged gradient lives on vlist[0]'s device
        out_shards = fn([vlist[i].data for i in order], out_dev=devs[0])
        return NDArray(out_shards, ctx=vlist[0]._ctx)

    # ------------------------------------------------------------ optimizer
    def set_optimizer(self, optimizer) -> None:
        from .optimizer import get_updater

        self._optimizer = optimizer
        self._set_updater(get_updater(optimizer))

    def _set_updater(self, updater: Callable) -> None:
        self._updater = updater

    def save_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # ---------------------------------------------------------------- roles
    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    def barrier(self) -> None:
        from .engine import waitall

        telemetry.counter("kvstore_barrier_total").inc()
        waitall()

    def _barrier_before_exit(self):
        pass

    def __del__(self):
        pass


class DistKVStore(KVStore):
    """Multi-host kvstore (``dist_sync`` / ``dist_async`` /
    ``dist_device_sync``).

    Two transports (SURVEY.md §5.8 redesign):

    - **sync** types ride jax.distributed XLA collectives: push psums the
      gradient across processes over DCN in one jitted ``shard_map``
      collective, and every worker runs the identical updater on the
      identical summed gradient — numerically the reference's server-side
      single update replicated, which the nightly ``dist_sync_kvstore.py``
      contract (value == rate·nrepeat·nworker+1) validates.
    - **``dist_async``** keeps the reference's true async semantics
      (``kvstore_dist_server.h:154`` async branch: server applies each
      worker's gradient immediately, no merge): when server processes are
      launched (``tools/launch.py -s N``), pushes stream to the TCP
      parameter server (``ps.py``), whose updater races across workers by
      design.  Without servers it degrades to the sync collective path.
    """

    def __init__(self, kv_type: str):
        super().__init__(kv_type)
        self._ps_client = None
        self._psum_allreduce_cache: Dict[tuple, Callable] = {}
        env_servers = int(os.environ.get("DMLC_NUM_SERVER", "0"))
        if env_servers > 0:
            # server processes were launched: the PS transport carries this
            # store — sync types merge-at-server, dist_async applies per
            # push (kvstore.cc:34-57 role split)
            self._init_ps()
        else:
            self._init_distributed()

    # --------------------------------------------------------- ps transport
    def _init_ps(self):
        from . import ps

        rank = int(os.environ.get("DMLC_WORKER_ID",
                                  os.environ.get("TP_PROCESS_ID", "0")))
        self._rank = rank
        self._size = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        # server-replacement recovery (TP_PS_RECOVERY) is only sound for
        # dist_async: each push applies alone, so a replacement re-seeded
        # from worker weights resumes cleanly.  A sync-mode merge that
        # lost a member cannot be reconstructed — sync jobs fail cleanly.
        recover = None if self.type == "dist_async" else False
        self._ps_client = ps.PSClient(rank, recover_servers=recover)
        if self._rank == 0:
            # rank 0 toggles server sync mode at create (kvstore.cc:47-50)
            self._ps_client.set_sync(self.type != "dist_async")
        self._ps_client.barrier("create")

    # -------------------------------------------------- collective transport
    def _init_distributed(self):
        import jax

        self._rank = 0
        self._size = 1
        coord = get_env("KVSTORE_COORDINATOR",
                        os.environ.get("DMLC_PS_ROOT_URI"))
        if jax.process_count() > 1:
            self._rank = jax.process_index()
            self._size = jax.process_count()
        elif coord:
            # explicit rendezvous (tools/launch.py analog): env gives
            # coordinator address + process rank/count
            n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
            r = int(os.environ.get("DMLC_WORKER_ID",
                                   os.environ.get("TP_PROCESS_ID", "0")))
            port = os.environ.get("JAX_COORD_PORT", "9876")
            if n > 1:
                jax.distributed.initialize(
                    coordinator_address="%s:%s" % (coord, port),
                    num_processes=n, process_id=r)
                self._rank = jax.process_index()
                self._size = jax.process_count()

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._size

    def _allreduce(self, arr: NDArray) -> NDArray:
        """One-collective psum across processes (DCN all-reduce).

        Builds a (P, *shape) global array over a 1-d process mesh — one
        device per process — and reduces with a jitted shard_map psum,
        replacing the old allgather + host-side sum (O(P) traffic and a
        host round-trip where one collective belongs).
        """
        if self._size == 1:
            return arr
        import jax

        data = arr.data
        if telemetry.enabled():
            telemetry.counter("kvstore_allreduce_total").inc()
            telemetry.counter("kvstore_allreduce_bytes_total").inc(
                _nd_bytes(arr))
        sig = (tuple(data.shape), str(data.dtype))
        fn = self._psum_allreduce_cache.get(sig)
        if fn is None:
            fn = _build_process_psum(data.shape, data.dtype)
            self._psum_allreduce_cache[sig] = fn
        return NDArray(fn(data), ctx=arr._ctx)

    def init(self, key, value) -> None:
        if self._ps_client is None:
            super().init(key, value)
            if self._size > 1:
                # broadcast rank 0's initial value so every worker starts
                # from identical weights (the reference's server holds the
                # rank-0 init: kvstore_dist.h init + first pull); psum of
                # (rank==0 ? v : 0) is a broadcast in one collective
                keys, _ = _key_value(key, value)
                for k in keys:
                    v = self._store[k]
                    contrib = v if self._rank == 0 else \
                        NDArray(v.data * 0, ctx=v._ctx)
                    self._store[k]._set_data(self._allreduce(contrib).data)
            return
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            v0 = v[0] if isinstance(v, list) else v
            self._store[k] = v0.copy()
            if self._rank == 0:
                self._ps_client.init(k, v0.asnumpy())
        self.barrier()

    def push(self, key, value, priority: int = 0) -> None:
        keys, values = _key_value(key, value)
        _tele = telemetry.enabled()
        for k, vlist in zip(keys, values):
            if not isinstance(vlist, list):
                vlist = [vlist]
            if _tele:
                telemetry.counter("kvstore_push_total").inc()
                telemetry.counter("kvstore_push_bytes_total").inc(
                    sum(_nd_bytes(v) for v in vlist))
            merged = self._reduce(vlist)          # intra-process devices
            if self._ps_client is not None:
                # async: the server applies immediately; nothing local
                self._ps_client.push(k, merged.asnumpy())
                continue
            merged = self._allreduce(merged)      # inter-process DCN
            if self._updater is not None:
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k]._set_data(merged.data)

    def pull(self, key, out=None, priority: int = 0) -> None:
        if self._ps_client is None:
            return super().pull(key, out=out, priority=priority)
        keys, outs = _key_value(key, out)
        _tele = telemetry.enabled()
        for k, olist in zip(keys, outs):
            if not isinstance(olist, list):
                olist = [olist]
            val = self._ps_client.pull(k, self._store[k].asnumpy())
            if _tele:
                telemetry.counter("kvstore_pull_total").inc()
                telemetry.counter("kvstore_pull_bytes_total").inc(
                    val.nbytes * len(olist))
            for o in olist:
                o._set_data(_place_like(NDArray(val), o))

    def set_optimizer(self, optimizer) -> None:
        if self._ps_client is not None:
            # the updater runs server-side (kvstore_dist_server.h updater)
            self._optimizer = optimizer
            if self._rank == 0:
                self._ps_client.set_optimizer(optimizer)
            self.barrier()
            return
        super().set_optimizer(optimizer)

    def get_dead_nodes(self, timeout=None):
        """Nodes whose heartbeat is stale (``ps::Postoffice::GetDeadNodes``
        via kvstore_dist.h:177-190); empty on the collective transport,
        where jax.distributed owns liveness.  ``timeout`` defaults to the
        ``TP_PS_DEADNODE_TIMEOUT`` env knob (60 s)."""
        if self._ps_client is not None:
            return self._ps_client.dead_nodes(timeout)
        return []

    def barrier(self) -> None:
        if self._ps_client is not None:
            from .engine import waitall

            telemetry.counter("kvstore_barrier_total").inc()
            waitall()
            self._ps_client.barrier()
            return
        super().barrier()
        if self._size > 1:
            from jax.experimental.multihost_utils import sync_global_devices

            sync_global_devices("kvstore_barrier")

    def _barrier_before_exit(self):
        if self._ps_client is not None:
            self._ps_client.finalize()

    def __del__(self):
        try:
            self._barrier_before_exit()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _build_psum(devices, shape, dtype):
    """Compile a one-collective all-reduce over ``devices``.

    Returns ``fn(list_of_per_device_arrays) -> replicated jax.Array``.
    The input shards form a (N, *shape) global array sharded on axis 0 of a
    1-d mesh; ``shard_map(lax.psum)`` reduces it to a fully-replicated
    result in a single XLA program (ICI all-reduce on a TPU mesh).
    """
    import jax
    import numpy as _np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from .parallel.mesh import shard_map_fn

    shard_map = shard_map_fn()

    mesh = Mesh(_np.asarray(devices), ("dev",))
    in_sharding = NamedSharding(mesh, P("dev"))
    n = len(devices)

    @jax.jit
    def reduce_fn(x):
        return shard_map(
            lambda s: jax.lax.psum(s[0], "dev"), mesh=mesh,
            in_specs=P("dev"), out_specs=P())(x)

    def fn(shards, out_dev=None):
        global_shape = (n,) + tuple(shape)
        arrs = [jax.device_put(s.reshape((1,) + tuple(shape)), d)
                for s, d in zip(shards, devices)]
        x = jax.make_array_from_single_device_arrays(
            global_shape, in_sharding, arrs)
        out = reduce_fn(x)
        # the result is replicated on every contributing device; hand back
        # the zero-copy local shard on the requested "merge device" (the
        # device the updater then runs on, comm.h:344 round-robin analog)
        tgt = out_dev if out_dev is not None else devices[0]
        for shard in out.addressable_shards:
            if shard.device == tgt:
                return shard.data
        return out.addressable_shards[0].data

    return fn


def _build_process_psum(shape, dtype):
    """Compile a cross-process all-reduce: one device per process, global
    (P, *shape) array, shard_map psum → replicated result; returns the
    local shard."""
    import jax
    import numpy as _np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from .parallel.mesh import shard_map_fn

    shard_map = shard_map_fn()

    procs = jax.process_count()
    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, d)
    devices = [by_proc[i] for i in range(procs)]
    mesh = Mesh(_np.asarray(devices), ("proc",))
    in_sharding = NamedSharding(mesh, P("proc"))
    local_dev = by_proc[jax.process_index()]

    @jax.jit
    def reduce_fn(x):
        return shard_map(lambda s: jax.lax.psum(s[0], "proc"), mesh=mesh,
                         in_specs=P("proc"), out_specs=P())(x)

    def fn(data):
        local = jax.device_put(data.reshape((1,) + tuple(shape)), local_dev)
        x = jax.make_array_from_single_device_arrays(
            (procs,) + tuple(shape), in_sharding, [local])
        out = reduce_fn(x)
        return out.addressable_shards[0].data

    return fn


def _nd_bytes(arr) -> int:
    """Payload size of an NDArray-ish value (shape × itemsize; safe on
    anything exposing .shape and .dtype)."""
    try:
        size = 1
        for s in arr.shape:
            size *= int(s)
        return size * np.dtype(arr.dtype).itemsize
    except (TypeError, ValueError, AttributeError):
        return 0


def _key_value(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value)
    return [key], [value]


def _updater_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _place_like(src: NDArray, dst: NDArray):
    import jax

    return jax.device_put(src.data.astype(dst.dtype),
                          dst.context.jax_device)
