"""KVStore — the distributed communication facade.

Reference analog: ``include/mxnet/kvstore.h`` + ``src/kvstore/*`` —
``local`` (CPU-staged reduce), ``device`` (GPU P2P reduce), ``dist_sync`` /
``dist_async`` / ``dist_device_sync`` (ps-lite parameter server).

TPU-native redesign (SURVEY.md §5.8): the Init/Push/Pull/updater/Barrier API
is preserved so Module/Trainer port unchanged, but the transport is:

- ``local``: host-side tree reduce (numpy/jax on host devices);
- ``device``: XLA all-reduce across the in-process device mesh — a single
  fused ``psum`` per key group replaces CommDevice's P2P gather-scatter
  (``src/kvstore/comm.h:222``), riding ICI on a real TPU pod;
- ``dist_*``: multi-process collectives over jax.distributed (DCN between
  hosts).  The ps-lite scheduler's rendezvous role is played by the JAX
  coordination service; ``rank``/``num_workers``/``Barrier`` map to
  process_index/process_count/global sync.  Per SURVEY.md §3.5 sync-mode
  math: gradients are *summed* across workers then the updater runs once —
  exactly what a psum all-reduce computes.
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError, get_env
from .ndarray.ndarray import NDArray
from .ndarray import zeros as nd_zeros

__all__ = ["KVStore", "create"]


def create(name: str = "local") -> "KVStore":
    """``mx.kv.create`` — factory (``src/kvstore/kvstore.cc:34-57``)."""
    name = name.lower()
    if name not in ("local", "local_allreduce_cpu", "local_allreduce_device",
                    "device", "dist_sync", "dist_async", "dist_device_sync",
                    "dist"):
        raise MXNetError("unknown kvstore type %s" % name)
    if name.startswith("dist"):
        return DistKVStore(name)
    return KVStore(name)


class KVStore:
    """Single-process kvstore (types ``local`` and ``device``)."""

    def __init__(self, kv_type: str = "local"):
        self.type = kv_type
        self._store: Dict[Any, NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None

    # ------------------------------------------------------------------ api
    def init(self, key, value) -> None:
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %s already initialized" % k)
            self._store[k] = v[0].copy() if isinstance(v, list) else v.copy()

    def push(self, key, value, priority: int = 0) -> None:
        """Aggregate (sum) pushed values per key; run updater if set
        (``KVStoreLocal::Push``, kvstore_local.h:83)."""
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if not isinstance(vlist, list):
                vlist = [vlist]
            merged = self._reduce(vlist)
            if self._updater is not None:
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k]._set_data(merged.data)

    def pull(self, key, out=None, priority: int = 0) -> None:
        keys, outs = _key_value(key, out)
        for k, olist in zip(keys, outs):
            if not isinstance(olist, list):
                olist = [olist]
            src = self._store[k]
            for o in olist:
                # broadcast to each destination's device
                o._set_data(_place_like(src, o))

    def row_sparse_pull(self, *a, **k):
        raise MXNetError("sparse storage is not supported")

    # ------------------------------------------------------------ reduction
    def _reduce(self, vlist: List[NDArray]) -> NDArray:
        """Sum a list of per-device gradients.

        ``device`` semantics: arrays may live on different mesh devices; jax
        resolves cross-device adds via ICI transfers, and inside a jit step
        the same reduction lowers to one XLA all-reduce.
        """
        if len(vlist) == 1:
            return vlist[0]
        import jax

        # stage onto the merge device (CommCPU pinned-buffer copy /
        # CommDevice merge-buffer analog), then tree-sum
        dev = next(iter(vlist[0].data.devices()))
        acc = vlist[0].data
        for v in vlist[1:]:
            acc = acc + jax.device_put(v.data, dev)
        return NDArray(acc, ctx=vlist[0]._ctx)

    # ------------------------------------------------------------ optimizer
    def set_optimizer(self, optimizer) -> None:
        from .optimizer import get_updater

        self._optimizer = optimizer
        self._set_updater(get_updater(optimizer))

    def _set_updater(self, updater: Callable) -> None:
        self._updater = updater

    def save_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname: str) -> None:
        if self._updater is None:
            raise MXNetError("no updater/optimizer set")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # ---------------------------------------------------------------- roles
    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    def barrier(self) -> None:
        from .engine import waitall

        waitall()

    def _barrier_before_exit(self):
        pass

    def __del__(self):
        pass


class DistKVStore(KVStore):
    """Multi-host kvstore over jax.distributed (``dist_sync`` /
    ``dist_async`` / ``dist_device_sync``).

    Worker-side semantics mirror ``KVStoreDist`` (kvstore_dist.h): push
    all-reduces the gradient across processes (sum), every process runs the
    identical updater on the identical summed gradient — numerically the
    reference's server-side single update replicated, which the nightly
    ``dist_sync_kvstore.py`` contract (value == rate·nrepeat·nworker+1)
    validates.
    """

    def __init__(self, kv_type: str):
        super().__init__(kv_type)
        self._init_distributed()

    def _init_distributed(self):
        import jax

        self._rank = 0
        self._size = 1
        coord = get_env("KVSTORE_COORDINATOR",
                        os.environ.get("DMLC_PS_ROOT_URI"))
        if jax.process_count() > 1:
            self._rank = jax.process_index()
            self._size = jax.process_count()
        elif coord:
            # explicit rendezvous (tools/launch.py analog): env gives
            # coordinator address + process rank/count
            n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
            r = int(os.environ.get("TP_PROCESS_ID", "0"))
            port = os.environ.get("DMLC_PS_ROOT_PORT", "9876")
            if n > 1:
                jax.distributed.initialize(
                    coordinator_address="%s:%s" % (coord, port),
                    num_processes=n, process_id=r)
                self._rank = jax.process_index()
                self._size = jax.process_count()

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._size

    def _allreduce(self, arr: NDArray) -> NDArray:
        if self._size == 1:
            return arr
        import jax
        import jax.numpy as jnp
        from jax.experimental.multihost_utils import (
            process_allgather)

        summed = process_allgather(arr.data).sum(axis=0)
        return NDArray(jnp.asarray(summed), ctx=arr._ctx)

    def push(self, key, value, priority: int = 0) -> None:
        keys, values = _key_value(key, value)
        for k, vlist in zip(keys, values):
            if not isinstance(vlist, list):
                vlist = [vlist]
            merged = self._reduce(vlist)          # intra-process devices
            merged = self._allreduce(merged)      # inter-process DCN
            if self._updater is not None:
                self._updater(_updater_key(k), merged, self._store[k])
            else:
                self._store[k]._set_data(merged.data)

    def barrier(self) -> None:
        super().barrier()
        if self._size > 1:
            from jax.experimental.multihost_utils import sync_global_devices

            sync_global_devices("kvstore_barrier")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _key_value(key, value):
    if isinstance(key, (list, tuple)):
        return list(key), list(value)
    return [key], [value]


def _updater_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _place_like(src: NDArray, dst: NDArray):
    import jax

    return jax.device_put(src.data.astype(dst.dtype),
                          dst.context.jax_device)
