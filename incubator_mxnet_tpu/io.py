"""Data iterators (``python/mxnet/io.py`` + ``src/io/`` capabilities).

DataIter / DataBatch / DataDesc contract is the reference's; NDArrayIter,
CSVIter, MNISTIter and the Resize/Prefetching wrappers are provided here,
ImageRecordIter in :mod:`.image` (stage 7 per SURVEY.md §7).  The prefetcher
is a thread double-buffer — the TPU-native equivalent of
``iter_prefetcher.h``'s ``dmlc::ThreadedIter``, overlapping host batch prep
with device compute.
"""
from __future__ import annotations

import gzip
import os
import struct
import threading
from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import telemetry
from .base import MXNetError
from .context import Context, cpu
from .ndarray import array as nd_array
from .ndarray.ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "MNISTIter", "ResizeIter", "PrefetchingIter", "DeviceQueueIter",
           "ImageRecordIter", "ImageRecordUInt8Iter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator base (reference ``io.py:174``)."""

    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    __next__ = next

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference ``io.py:513``): dict/list/
    single array data+label, shuffle, pad/discard/roll_over last batch."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.idx = np.arange(self.num_data)
        if shuffle:
            np.random.shuffle(self.idx)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        if last_batch_handle == "discard":
            n = self.num_data - self.num_data % batch_size
            self.idx = self.idx[:n]
        self.data_list = [x[1] for x in self.data] + \
            [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.cursor = -batch_size

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                self.cursor > len(self.idx):
            self.cursor = -self.batch_size + (self.cursor % len(self.idx))
        else:
            self.cursor = -self.batch_size

    def iter_next(self) -> bool:
        self.cursor += self.batch_size
        return self.cursor < len(self.idx)

    def _getdata(self, data_source):
        assert self.cursor < len(self.idx)
        end = self.cursor + self.batch_size
        if end <= len(self.idx):
            sel = self.idx[self.cursor:end]
        else:  # pad wraps around
            pad = end - len(self.idx)
            sel = np.concatenate([self.idx[self.cursor:], self.idx[:pad]])
        return [nd_array(x[1][sel]) for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self) -> int:
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > len(self.idx):
            return self.cursor + self.batch_size - len(self.idx)
        return 0


def _init_data(data, allow_empty: bool, default_name: str):
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.asarray(v)))
    return out


class CSVIter(DataIter):
    """CSV reader (``src/io/iter_csv.cc`` capability)."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32,
                          ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[1:] == (1,):
                label = label[:, 0]
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    __next__ = next


class MNISTIter(DataIter):
    """MNIST idx-format reader (``src/io/iter_mnist.cc``).  Reads the
    classic ubyte(.gz) files; if absent, generates a deterministic synthetic
    digit-like dataset so examples/tests run hermetically (zero-egress
    environment)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, num_examples=None, **kwargs):
        super().__init__(batch_size)
        data, lab = self._load(image, label, seed, num_examples)
        if flat:
            data = data.reshape(data.shape[0], -1)
        else:
            data = data.reshape((-1, 1, 28, 28))
        self._inner = NDArrayIter(data, lab, batch_size=batch_size,
                                  shuffle=shuffle)

    @staticmethod
    def _load(image, label, seed, num_examples):
        if os.path.exists(image) or os.path.exists(image + ".gz"):
            data = _read_idx(image)
            lab = _read_idx(label)
            data = data.astype(np.float32) / 255.0
            return data, lab.astype(np.float32)
        # synthetic fallback: 10 fixed class-template images + noise.
        # Templates come from a FIXED seed so train (seed=0) and val
        # (seed=1) iterators share the same class→image mapping and a
        # model trained on one generalizes to the other; ``seed`` only
        # drives the per-sample draw.
        n = num_examples or 6000
        templates = np.random.RandomState(42).rand(
            10, 28, 28).astype(np.float32)
        rng = np.random.RandomState(seed)
        # warm the generator before drawing labels: MT19937's first draws
        # after a small integer seed are poorly mixed, and an unwarmed
        # label stream measurably stalls LeNet convergence
        rng.rand(8192)
        lab = rng.randint(0, 10, n)
        data = templates[lab] + rng.randn(n, 28, 28).astype(np.float32) * 0.3
        return np.clip(data, 0, 1), lab.astype(np.float32)

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    __next__ = next


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if not os.path.exists(path) else open
    if not os.path.exists(path):
        path = path + ".gz"
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (reference ``io.py:275``)."""

    def __init__(self, data_iter: DataIter, size: int,
                 reset_internal: bool = True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur == self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch

    __next__ = next


class PrefetchingIter(DataIter):
    """Thread double-buffer prefetcher (``iter_prefetcher.h`` /
    reference ``io.py:340``): hides host-side batch prep behind device
    compute — on TPU this overlaps input pipeline with step execution."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, list):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.n_iter = len(iters)
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None] * self.n_iter
        self.next_batch = [None] * self.n_iter
        self._errors: List[Optional[BaseException]] = [None] * self.n_iter

        def prefetch(i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                except Exception as exc:
                    # anything else must NOT kill the thread silently —
                    # data_ready would never set and the consumer would
                    # block forever in iter_next(); record it for
                    # re-raise on the consumer thread instead
                    self._errors[i] = exc
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch, args=[i], daemon=True)
            for i in range(self.n_iter)]
        for t in self.prefetch_threads:
            t.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def close(self, timeout: Optional[float] = None):
        """Stop the prefetch threads deterministically (don't rely on
        ``__del__`` — GC order at interpreter shutdown is undefined and
        a still-parked worker would pin its iterators alive)."""
        self.started = False
        for e in self.data_taken:
            e.set()
        for t in self.prefetch_threads:
            t.join(timeout=timeout)

    def __del__(self):
        try:
            self.close(timeout=2.0)  # bounded: never hang process exit
        except Exception:
            pass

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        self._errors = [None] * self.n_iter
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self) -> bool:
        for e in self.data_ready:
            e.wait()
        for exc in self._errors:
            if exc is not None:
                # stay armed (ready set, taken clear): every subsequent
                # call re-raises fast instead of handing the dead slot
                # back to the worker
                raise exc
        if any(b is None for b in self.next_batch):
            # ANY exhausted source ends the epoch — index 0 alone would
            # zip mismatched-length iters into a crash below
            return False
        self.current_batch = DataBatch(
            sum([b.data for b in self.next_batch], []),
            sum([(b.label or []) for b in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    __next__ = next

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class DeviceQueueIter(DataIter):
    """Device-staging prefetcher: wrap any ``DataIter`` and keep the
    next K batches RESIDENT ON DEVICE (``docs/input_pipeline.md``).

    A background thread pulls host batches from the wrapped iterator and
    ``jax.device_put``s each array — with the step's batch sharding when
    a ``mesh``/``sharding`` is given — into a bounded queue of depth
    ``TP_DEVICE_PREFETCH`` (default 2).  The H2D copy therefore overlaps
    the running step instead of serializing in front of it; the train
    loop's ``next()`` returns already-staged arrays that
    ``FusedTrainStep`` / the executor consume without a further put.

    ``mesh=`` reuses the fused-step batch placement
    (:func:`..parallel.mesh.data_parallel_spec`: batch axis over ``dp``,
    rest replicated); ``sharding=`` pins an explicit
    ``jax.sharding.Sharding``; ``device=`` a single device; default is
    the first local device.  Telemetry: ``input_wait_seconds`` (how long
    the consumer waited — the input-starvation signal), ``h2d_bytes``,
    ``device_prefetch_batches_total``.
    """

    def __init__(self, data_iter: DataIter, depth: Optional[int] = None,
                 mesh=None, sharding=None, device=None):
        from .base import get_env

        super().__init__(data_iter.batch_size)
        if sum(x is not None for x in (mesh, sharding, device)) > 1:
            raise MXNetError(
                "pass at most one of mesh=, sharding=, device=")
        self.data_iter = data_iter
        if depth is None:
            depth = get_env("DEVICE_PREFETCH", 2, int)
        self.depth = max(1, int(depth))
        self._mesh = mesh
        self._sharding = sharding
        self._device = device
        self._queue = None
        self._worker = None
        self._stop = False
        self._start()

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    # ---------------------------------------------------------- staging
    def _placement(self, ndim: int):
        if self._sharding is not None:
            return self._sharding
        if self._mesh is not None:
            from .parallel.mesh import data_parallel_spec

            return data_parallel_spec(self._mesh, ndim)
        if self._device is not None:
            return self._device
        import jax

        return jax.devices()[0]

    def _stage(self, arr):
        """One array → device, on the WORKER thread (H2D overlaps the
        running step)."""
        import jax

        a = arr.data if isinstance(arr, NDArray) else arr
        host = not isinstance(a, jax.Array)
        if host:
            a = np.ascontiguousarray(a)
        dev = jax.device_put(a, self._placement(a.ndim))
        if host:
            telemetry.counter("h2d_bytes").inc(int(a.nbytes))
        return NDArray(dev)

    def _start(self):
        import queue as queue_mod

        self._queue = queue_mod.Queue(maxsize=self.depth)
        self._stop = False

        def worker():
            try:
                while not self._stop:
                    try:
                        batch = self.data_iter.next()
                    except StopIteration:
                        self._queue.put(None)
                        return
                    staged = DataBatch(
                        [self._stage(d) for d in batch.data],
                        [self._stage(l) for l in (batch.label or [])],
                        pad=batch.pad, index=batch.index,
                        bucket_key=batch.bucket_key,
                        provide_data=batch.provide_data,
                        provide_label=batch.provide_label)
                    telemetry.counter(
                        "device_prefetch_batches_total").inc()
                    self._queue.put(staged)
            except Exception as exc:  # surface to the consumer, no hang
                self._queue.put(exc)

        self._worker = threading.Thread(target=worker, daemon=True)
        self._worker.start()

    # --------------------------------------------------------- consumer
    def next(self) -> DataBatch:
        import time as time_mod

        t0 = time_mod.monotonic()
        item = self._queue.get()
        # the starvation signal: ~0 when staging keeps ahead of compute
        telemetry.histogram("input_wait_seconds").observe(
            time_mod.monotonic() - t0)
        if item is None:
            # keep the sentinel so repeated next() keeps raising rather
            # than blocking on the dead worker
            self._queue.put(None)
            raise StopIteration
        if isinstance(item, Exception):
            self._queue.put(item)  # re-arm: fail fast on every call
            raise item
        return item

    __next__ = next

    # -------------------------------------------------------- lifecycle
    def _drain_worker(self, deadline: Optional[float] = None):
        import queue as queue_mod
        import time as time_mod

        self._stop = True
        if self._worker is None:
            return
        t0 = time_mod.monotonic()
        while self._worker.is_alive():
            if deadline is not None \
                    and time_mod.monotonic() - t0 > deadline:
                return
            try:
                self._queue.get(timeout=0.1)
            except queue_mod.Empty:
                pass
        self._worker.join()

    def reset(self):
        # drain so the dead epoch's worker cannot race the next epoch's
        # worker on the shared inner iterator
        self._drain_worker()
        self.data_iter.reset()
        self._start()

    def close(self, timeout: Optional[float] = None):
        """Stop the staging worker deterministically."""
        self._drain_worker(deadline=timeout)

    def __del__(self):
        try:
            self.close(timeout=2.0)  # bounded: never hang process exit
        except Exception:
            pass


class ImageRecordIter(DataIter):
    """RecordIO pack of encoded images → multithreaded decode/augment →
    device-ready NCHW batches.

    Reference analog: the C++ ``ImageRecordIter`` chain
    (``src/io/iter_image_recordio_2.cc``: parser thread pool → batch
    loader → normalize → prefetcher).  Host-side here by design: on TPU
    systems input pipelines run on host CPU; ``preprocess_threads`` maps
    to a thread pool (cv2 releases the GIL) and prefetching to a
    background queue exactly like ``iter_prefetcher.h`` double-buffered.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 rand_crop=False, rand_mirror=False, resize=0,
                 preprocess_threads=4, prefetch_buffer=4, label_width=1,
                 data_name="data", label_name="softmax_label",
                 round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        from . import image as image_mod

        self._dtype = np.dtype(dtype)
        mean = None
        std = None
        if mean_r or mean_g or mean_b:
            mean = np.array([mean_r, mean_g, mean_b], dtype=np.float32)
        if std_r != 1.0 or std_g != 1.0 or std_b != 1.0:
            std = np.array([std_r, std_g, std_b], dtype=np.float32)

        if self._dtype == np.uint8:
            # uint8 transport (reference ImageRecordUInt8Iter,
            # iter_image_recordio_2.cc:612): crop/resize/flip only on the
            # host; cast + mean/std normalize belong on the DEVICE, where
            # they fuse into the first conv — and the host moves 4× fewer
            # bytes per batch
            if mean is not None or std is not None or scale != 1.0:
                raise MXNetError(
                    "dtype='uint8' keeps normalization on the device; "
                    "drop mean_*/std_*/scale or use dtype='float32'")
            aug = image_mod.CreateAugmenter(
                data_shape, resize=resize, rand_crop=rand_crop,
                rand_mirror=rand_mirror, cast=False)
            # the uint8 chain is exactly decode→[resize]→crop→[flip]:
            # one native C call covers it (libjpeg decode + bilinear
            # resize + crop + mirror, GIL-free — the reference's C++
            # decode stage).  Python chain kept as the fallback for
            # non-JPEG payloads / missing native lib / undersized
            # images.  (Native resize is bilinear; the python chain's
            # inter_method applies only on its fallback path.)
            if data_shape[0] == 3:
                self._native_recipe = (int(resize), bool(rand_crop),
                                       bool(rand_mirror),
                                       (int(data_shape[1]),
                                        int(data_shape[2])))
            else:
                self._native_recipe = None
        else:
            self._native_recipe = None
            aug = image_mod.CreateAugmenter(
                data_shape, resize=resize, rand_crop=rand_crop,
                rand_mirror=rand_mirror, mean=mean, std=std)
        self._scale = scale
        self._inner = image_mod.ImageIter(
            batch_size, data_shape, label_width=label_width,
            path_imgrec=path_imgrec, path_imgidx=path_imgidx,
            shuffle=shuffle, aug_list=aug, data_name=data_name,
            label_name=label_name)
        self.provide_data = [
            DataDesc(d.name, d.shape, self._dtype, d.layout)
            for d in self._inner.provide_data]
        self.provide_label = self._inner.provide_label
        self._threads = max(1, int(preprocess_threads))
        self._prefetch = max(1, int(prefetch_buffer))
        self._pool = None
        self._queue = None
        self._worker = None
        self._stop = False
        self._start_prefetch()

    # --- background prefetch (analog of iter_prefetcher.h) ---------------
    def _decode_one(self, item):
        """Decode + augment one raw record → list of CHW float arrays.

        Runs on a pool worker; cv2 decode releases the GIL so
        ``preprocess_threads`` workers scale like the reference's parser
        thread pool (``iter_image_recordio_2.cc:46``).
        """
        from . import image as image_mod

        label, s = item
        if self._native_recipe is not None:
            import random as _random

            from . import native

            resize, rand_crop, rand_mirror, (ch, cw) = \
                self._native_recipe
            buf = s if isinstance(s, bytes) else bytes(s)
            cy = cx = -1
            ok = True
            if rand_crop:
                dims = native.decoded_dims(buf, resize)
                if dims is None or dims[0] < ch or dims[1] < cw:
                    ok = False
                else:
                    cy = _random.randint(0, dims[0] - ch)
                    cx = _random.randint(0, dims[1] - cw)
            if ok:
                flip = rand_mirror and _random.random() < 0.5
                out = native.decode_resize_crop(
                    buf, ch, cw, resize=resize, crop_y=cy, crop_x=cx,
                    flip=flip)
                if out is not None:
                    return label, [out]
            # fall through: python decode+augment path

        from .image.image import _imdecode_np

        # numpy end-to-end: decode and every augmenter stay on the host
        # (image._wrap_like) — no per-image device round-trips
        data = [_imdecode_np(s)]
        for aug in self._inner.auglist:
            data = [ret for src in data for ret in aug(src)]
        out = []
        for d in data:
            arr = d.asnumpy() if hasattr(d, "asnumpy") else np.asarray(d)
            if self._dtype == np.uint8 and arr.dtype == np.uint8:
                # uint8 stays HWC: the batch assembler does the CHW
                # transpose for the whole batch at once (native C++ when
                # available — iter_batchloader.h analog)
                out.append(np.ascontiguousarray(arr))
            else:
                out.append(np.ascontiguousarray(
                    arr.transpose(2, 0, 1), dtype=self._dtype))
        return label, out

    def _start_prefetch(self):
        import queue
        from multiprocessing.pool import ThreadPool

        self._queue = queue.Queue(maxsize=self._prefetch)
        self._stop = False
        if self._pool is None:
            self._pool = ThreadPool(self._threads)

        inner = self._inner

        def worker():
            bs = inner.batch_size
            c, h, w = inner.data_shape
            # decoded-but-unbatched outputs carry over between batches so
            # multi-output augmenters lose no samples
            carry = []
            exhausted = False
            try:
                while not self._stop:
                    while len(carry) < bs and not exhausted:
                        raw = []
                        try:
                            while len(raw) < bs:
                                raw.append(inner.next_sample())
                        except StopIteration:
                            exhausted = True
                        for label, arrs in self._pool.map(
                                self._decode_one, raw):
                            carry.extend((label, a) for a in arrs)
                    if not carry:
                        self._queue.put(None)
                        return
                    take, carry = carry[:bs], carry[bs:]
                    batch_data = np.zeros((bs, c, h, w),
                                          dtype=self._dtype)
                    label_shape = (bs, inner.label_width) \
                        if inner.label_width > 1 else (bs,)
                    batch_label = np.zeros(label_shape,
                                           dtype=np.float32)
                    imgs = [arr for _, arr in take]
                    hwc = (self._dtype == np.uint8 and imgs
                           and imgs[0].ndim == 3
                           and imgs[0].shape[-1] == c)
                    assembled = False
                    if hwc:
                        from . import native

                        # whole-batch HWC→CHW transpose in the native
                        # C++ assembler (GIL-free), numpy fallback below
                        assembled = native.assemble_batch(imgs,
                                                          batch_data)
                    for i, (label, arr) in enumerate(take):
                        if not assembled:
                            batch_data[i] = arr.transpose(2, 0, 1) \
                                if hwc else arr
                        if inner.label_width > 1:
                            batch_label[i] = np.asarray(label)[
                                :inner.label_width]
                        else:
                            batch_label[i] = np.asarray(
                                label).reshape(-1)[0]
                    if self._scale != 1.0:
                        batch_data *= self._scale
                    self._queue.put(DataBatch(
                        [nd_array(batch_data)], [nd_array(batch_label)],
                        pad=bs - len(take),
                        provide_data=self.provide_data,
                        provide_label=self.provide_label))
                    if exhausted and not carry:
                        self._queue.put(None)
                        return
            except Exception as exc:  # surface to the consumer, no hang
                self._queue.put(exc)

        self._worker = threading.Thread(target=worker, daemon=True)
        self._worker.start()

    def _drain_worker(self, deadline: Optional[float] = None):
        """Stop + drain until the prefetch worker exits (it could be
        blocked on a full queue); shared by reset() and close().
        ``deadline`` (seconds) bounds the wait — interpreter shutdown
        can kill the daemon thread in a state where is_alive() never
        flips, and an unbounded drain would hang process exit."""
        import queue
        import time as _time

        self._stop = True
        if self._worker is None:
            return
        t0 = _time.monotonic()
        while self._worker.is_alive():
            if deadline is not None and _time.monotonic() - t0 > deadline:
                return
            try:
                self._queue.get(timeout=0.1)
            except queue.Empty:
                pass
        self._worker.join()

    def reset(self):
        # drain so the dead epoch's worker cannot race the next epoch's
        # worker on the shared inner iterator
        self._drain_worker()
        self._inner.reset()
        self._start_prefetch()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            # keep the sentinel so repeated next() keeps raising rather
            # than blocking on the dead worker
            self._queue.put(None)
            raise StopIteration
        if isinstance(batch, Exception):
            # the worker is dead; re-arm the queue so every subsequent
            # next() fails fast instead of hanging
            self._queue.put(batch)
            raise batch
        return batch

    __next__ = next

    def close(self, timeout: Optional[float] = None):
        """Stop the prefetch worker and tear down the decode pool
        deterministically (a GC'd ThreadPool raises noisy errors at
        interpreter shutdown)."""
        self._drain_worker(deadline=timeout)
        if self._worker is not None and self._worker.is_alive():
            # timed-out drain: the worker may still be inside
            # pool.map — terminating the pool under it would raise in
            # the worker and leave it blocked on queue.put forever
            return
        if self._pool is not None:
            self._pool.terminate()
            self._pool = None

    def __del__(self):
        try:
            self.close(timeout=2.0)  # bounded: never hang process exit
        except Exception:
            pass


def ImageRecordUInt8Iter(*args, **kwargs):
    """uint8-transport record iterator (reference registration
    ``iter_image_recordio_2.cc:612``): decode/crop/flip on the host, cast
    + normalize on the device."""
    kwargs["dtype"] = "uint8"
    return ImageRecordIter(*args, **kwargs)
