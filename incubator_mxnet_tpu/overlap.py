"""Bounded async dispatch for the train loops.

Reference analog: the dependency engine's in-flight op window — the
reference lets steps run ahead of the python loop and throttles on the
engine queue (SURVEY.md §7, layer 0).  On the TPU port the analogous
throttle is a ring of per-step fence handles: each step contributes one
tiny device scalar that depends on that step's work, and the loop
host-reads the handle of the step N behind before dispatching further.

Why a host READ and not ``jax.block_until_ready``: on the axon platform
``block_until_ready`` returns at dispatch time, not execution time
(PERF.md §1) — an unfenced loop enqueues without bound (runaway memory,
useless latency numbers) while fencing EVERY step serializes H2D,
compute and readback.  Reading one scalar derived from step N-k keeps at
most k steps in flight: the true fence PERF.md validated, amortized over
the window.

``TP_MAX_INFLIGHT`` (default 2) sizes the window; 0 disables overlap and
restores the fully synchronous legacy loop.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional

import numpy as np

from . import telemetry, tracing
from .base import get_env

__all__ = ["max_inflight", "fence_handle", "InflightRing", "drain_target"]

_SLICE_FN = None


def max_inflight() -> int:
    """The ``TP_MAX_INFLIGHT`` window (default 2, floor 0)."""
    return max(0, int(get_env("MAX_INFLIGHT", 2, int)))


def fence_handle(arr):
    """A tiny device array that depends on ``arr``'s producing program.

    One jitted element slice — reading the result back later fences
    everything enqueued up to that program (in-order execution per
    device stream).  The handle is a fresh non-donated array, so it
    stays valid even when the producing step's other operands were
    donated and recycled by a later step.
    """
    global _SLICE_FN
    if arr is None:
        return None
    import jax

    if _SLICE_FN is None:
        _SLICE_FN = jax.jit(lambda a: a.reshape((-1,))[:1])
    return _SLICE_FN(arr)


class InflightRing:
    """Ring of per-step fence handles bounding dispatch depth.

    ``push(handle)`` admits one step; once more than ``depth`` handles
    are pending, the OLDEST is host-read (true fence) before returning —
    so at most ``depth`` steps are ever dispatched-but-unfenced.
    ``drain()`` fences everything (epoch end / before host readbacks
    that must see finished state).
    """

    def __init__(self, depth: int, scope: str = "module"):
        self.depth = max(0, int(depth))
        self.scope = scope
        self.high_water = 0
        self._pending: deque = deque()

    def __len__(self) -> int:
        return len(self._pending)

    @staticmethod
    def _wait(handle) -> None:
        # host-read one scalar: the only fence that provably waits for
        # device execution on every platform (PERF.md §1)
        tctx = tracing.train_context()
        if tctx is None:
            np.asarray(handle).ravel()[:1]
        else:
            t0 = time.monotonic()
            np.asarray(handle).ravel()[:1]
            # the fence is where overlapped device time surfaces on
            # the host — the span the step trace attributes waits to
            tracing.record(tctx, "train.fence", t0, time.monotonic())
        telemetry.counter("inflight_fences_total").inc()

    def push(self, handle: Optional[Any]) -> None:
        if handle is not None:
            self._pending.append(handle)
        while len(self._pending) > self.depth:
            self._wait(self._pending.popleft())
        n = len(self._pending)
        if n > self.high_water:
            self.high_water = n
        telemetry.gauge("inflight_depth", {"scope": self.scope}).set(n)
        telemetry.gauge("inflight_high_water",
                        {"scope": self.scope}).set(self.high_water)

    def drain(self) -> None:
        while self._pending:
            self._wait(self._pending.popleft())
        telemetry.gauge("inflight_depth", {"scope": self.scope}).set(0)


def drain_target(target) -> bool:
    """Fence a train step's in-flight work before a host snapshot.

    Checkpointing donated-buffer steps while TP_MAX_INFLIGHT>1 keeps
    earlier steps dispatched-but-unexecuted; a snapshot taken then could
    read buffers a queued step is about to recycle.  Prefer the target's
    own ``sync()`` (ring drain + true host-read fence); fall back to a
    bare ring ``drain()``.  Returns True when something was fenced.
    """
    sync = getattr(target, "sync", None)
    if callable(sync):
        sync()
        return True
    ring = getattr(target, "_ring", None)
    if isinstance(ring, InflightRing):
        ring.drain()
        return True
    return False
