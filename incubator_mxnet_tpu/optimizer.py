"""Optimizers (``python/mxnet/optimizer.py``, 992 LoC; 13 optimizers).

Each step dispatches to a fused update op from
``ops/optimizer_ops.py`` (the reference runs sgd_update/adam_update/… as
single engine ops, ``src/operator/tensor/optimizer_op.cc``) so inside a jit
train step XLA fuses the whole update.  The ``Updater`` closure is the
kvstore-side entry exactly as in the reference (``optimizer.py:940``).
"""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import numpy as np

from .base import Registry
from .ndarray import op_invoke, zeros
from .ndarray.ndarray import NDArray

__all__ = ["Optimizer", "SGD", "DCASGD", "NAG", "SGLD", "ccSGD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam",
           "Test", "Updater", "get_updater", "create", "register"]

_REG = Registry("optimizer")


def register(klass):
    _REG.register(klass)
    return klass


def create(name, **kwargs) -> "Optimizer":
    return _REG.get(name)(**kwargs)


class Optimizer:
    """Base optimizer: lr/wd multipliers, gradient rescale/clip, per-index
    update counts (reference ``Optimizer`` base)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.clip_gradient = clip_gradient
        self.idx2name = dict(param_idx2name or {})
        self.sym = sym

    create_optimizer = staticmethod(create)

    # -- multipliers -------------------------------------------------------
    def set_lr_mult(self, args_lr_mult: Dict[str, float]):
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[str, float]):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # reference: no wd on bias/gamma/beta by default
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    # -- bookkeeping -------------------------------------------------------
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- API ---------------------------------------------------------------
    def create_state(self, index, weight: NDArray):
        return None

    def update(self, index, weight: NDArray, grad: NDArray, state) -> None:
        raise NotImplementedError

    def _common_kwargs(self, index):
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """SGD with momentum + optional multi-precision
    (reference ``optimizer.py:334``)."""

    def __init__(self, momentum=0.0, multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        mom = None
        w32 = None
        if self.multi_precision and weight.dtype == np.float16:
            w32 = weight.astype(np.float32)
        if self.momentum != 0.0:
            base = w32 if w32 is not None else weight
            mom = zeros(base.shape, ctx=base.context, dtype=base.dtype)
        if w32 is not None:
            return (mom, w32)
        return mom

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        w32 = None
        mom = state
        if isinstance(state, tuple):
            mom, w32 = state
        target = w32 if w32 is not None else weight
        g = grad.astype(np.float32) if w32 is not None else grad
        if mom is not None:
            op_invoke("sgd_mom_update", [target, g, mom],
                      dict(kw, momentum=self.momentum), out=target)
        else:
            op_invoke("sgd_update", [target, g], kw, out=target)
        if w32 is not None:
            weight._set_data(target.data.astype(weight.dtype))


@register
class NAG(SGD):
    """Nesterov accelerated SGD."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        mom, w32 = (state if isinstance(state, tuple) else (state, None))
        target = w32 if w32 is not None else weight
        g = grad.astype(np.float32) if w32 is not None else grad
        if mom is not None:
            op_invoke("nag_mom_update", [target, g, mom],
                      dict(kw, momentum=self.momentum), out=target)
        else:
            op_invoke("sgd_update", [target, g], kw, out=target)
        if w32 is not None:
            weight._set_data(target.data.astype(weight.dtype))


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = op_invoke("clip", [g], {"a_min": -self.clip_gradient,
                                        "a_max": self.clip_gradient})
        from .ndarray import random_normal

        noise = random_normal(loc=0.0, scale=math.sqrt(lr),
                              shape=weight.shape)
        weight._set_data((weight - lr / 2 * (g + wd * weight) + noise).data)


@register
class ccSGD(SGD):
    """Kept for API parity (reference ccSGD ≡ SGD in python at v0.11)."""


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous: Dict[Any, NDArray] = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = op_invoke("clip", [g], {"a_min": -self.clip_gradient,
                                        "a_max": self.clip_gradient})
        mom, prev = state
        comp = g + wd * weight + self.lamda * g * g * (weight - prev)
        if mom is not None:
            mom._set_data((self.momentum * mom - lr * comp).data)
            delta = mom
        else:
            delta = -lr * comp
        prev._set_data(weight.data)
        weight._set_data((weight + delta).data)


@register
class Adam(Optimizer):
    """Adam with bias correction (reference ``optimizer.py`` Adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        kw["lr"] = kw["lr"] * math.sqrt(coef2) / coef1
        mean, var = state
        op_invoke("adam_update", [weight, grad, mean, var],
                  dict(kw, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon), out=weight)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = op_invoke("clip", [g], {"a_min": -self.clip_gradient,
                                        "a_max": self.clip_gradient})
        history = state
        history._set_data((history + g * g).data)
        weight._set_data(
            (weight - lr * (g / op_invoke(
                "sqrt", [history + self.float_stable_eps]) + wd * weight)
             ).data)


@register
class RMSProp(Optimizer):
    """RMSProp (Tieleman) / RMSPropAlex (centered) —
    reference ``optimizer.py`` RMSProp with ``centered`` flag."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, ctx=weight.context),
                    zeros(weight.shape, ctx=weight.context),
                    zeros(weight.shape, ctx=weight.context))
        return zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self.centered:
            n, g, delta = state
            op_invoke("rmspropalex_update", [weight, grad, n, g, delta],
                      dict(kw, gamma1=self.gamma1, gamma2=self.gamma2,
                           epsilon=self.epsilon), out=weight)
        else:
            op_invoke("rmsprop_update", [weight, grad, state],
                      dict(kw, gamma1=self.gamma1, epsilon=self.epsilon),
                      out=weight)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context),
                zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = op_invoke("clip", [g], {"a_min": -self.clip_gradient,
                                        "a_max": self.clip_gradient})
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g + (1 - self.rho) * g * g).data)
        sqrt = lambda x: op_invoke("sqrt", [x])  # noqa: E731
        cur_delta = (sqrt(acc_delta + self.epsilon)
                     / sqrt(acc_g + self.epsilon) * g)
        acc_delta._set_data(
            (self.rho * acc_delta
             + (1 - self.rho) * cur_delta * cur_delta).data)
        weight._set_data((weight - cur_delta - wd * weight).data)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context),
                zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        z, n = state
        op_invoke("ftrl_update", [weight, grad, z, n],
                  dict(kw, lamda1=self.lamda1, beta=self.beta), out=weight)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context),
                zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = op_invoke("clip", [g], {"a_min": -self.clip_gradient,
                                        "a_max": self.clip_gradient})
        m_t, u_t = state
        m_t._set_data((self.beta1 * m_t + (1 - self.beta1) * g).data)
        u_t._set_data(op_invoke("_maximum",
                                [self.beta2 * u_t, op_invoke("abs", [g])]
                                ).data)
        weight._set_data((weight - lr * m_t / u_t).data)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (zeros(weight.shape, ctx=weight.context),
                zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = op_invoke("clip", [g], {"a_min": -self.clip_gradient,
                                        "a_max": self.clip_gradient})
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t._set_data((self.beta1 * m_t + (1 - self.beta1) * g).data)
        v_t._set_data((self.beta2 * v_t + (1 - self.beta2) * g * g).data)
        g_prime = g / (1.0 - self.m_schedule)
        m_t_prime = m_t / (1.0 - m_schedule_next)
        v_t_prime = v_t / (1.0 - self.beta2 ** t)
        m_t_bar = ((1.0 - momentum_t) * g_prime
                   + momentum_t_1 * m_t_prime)
        sqrt_v = op_invoke("sqrt", [v_t_prime])
        weight._set_data((weight - lr * m_t_bar
                          / (sqrt_v + self.epsilon)).data)


@register
class Test(Optimizer):
    """Test optimizer (reference ``Test``): w += g * rescale."""

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data((weight + grad * self.rescale_grad).data)


# ---------------------------------------------------------------------------
# Updater — the kvstore-side closure (reference ``optimizer.py:940``)
# ---------------------------------------------------------------------------


class Updater:
    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states: bytes) -> None:
        def tod(x):
            if isinstance(x, np.ndarray):
                from .ndarray import array as nd_array

                return nd_array(x)
            if isinstance(x, tuple):
                return tuple(tod(i) for i in x)
            return x

        self.states = {k: tod(v)
                       for k, v in pickle.loads(states).items()}

    def get_states(self) -> bytes:
        def toh(x):
            if isinstance(x, NDArray):
                return x.asnumpy()
            if isinstance(x, tuple):
                return tuple(toh(i) for i in x)
            return x

        return pickle.dumps({k: toh(v) for k, v in self.states.items()})


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)


# ---------------------------------------------------------------------------
# Fused-step update resolution — shared by FusedTrainStep and
# SymbolPipelineTrainStep (previously duplicated in both)
# ---------------------------------------------------------------------------

# optimizer name → (update op from ops/optimizer_ops.py, #state tensors)
FUSED_UPDATE_OPS = {
    "adam": ("adam_update", 2),
    "rmsprop": ("rmsprop_update", 1),
    "nag": ("nag_mom_update", 1),
    "ftrl": ("ftrl_update", 2),
}


def fused_update_plan(optimizer: str, opt_params: Dict[str, Any]):
    """Resolve ``optimizer`` to ``(update_op_name, n_states)`` for the
    one-program train steps, or None when unsupported.  ``sgd``
    dispatches on momentum (and drops the momentum attr when 0, like
    the reference's sgd_update/sgd_mom_update split); ``opt_params`` is
    mutated accordingly."""
    if optimizer == "sgd":
        if float(opt_params.get("momentum", 0.0)) != 0.0:
            return "sgd_mom_update", 1
        opt_params.pop("momentum", None)
        return "sgd_update", 0
    return FUSED_UPDATE_OPS.get(optimizer)
