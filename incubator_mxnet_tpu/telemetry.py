"""Unified runtime telemetry — process-wide metrics registry + exposition.

The reference engine captured per-op ``OprExecStat`` inside the profiler
(``src/engine/profiler.{h,cc}``); everything quantitative beyond spans
(throughput, queue depths, RPC counts) lived in ad-hoc log lines.  This
module is the shared metrics substrate those signals publish through:

- **Counters** (monotonic), **gauges** (last value) and **histograms**
  (count/sum/min/max + a bounded reservoir for quantiles), all
  thread-safe and key-addressed by ``name`` + optional label dict.
- **Env-gated**: metrics exist only when ``TP_TELEMETRY=1`` (or a test
  calls :func:`enable`).  When off, every accessor returns one shared
  no-op singleton — instrumentation sites cost a function call and
  allocate nothing, so the hot path is unchanged.
- **Exposition**: :func:`flush` appends one JSON snapshot per line to a
  JSONL sink (diffable against ``BENCH_*.json``), :func:`prometheus_text`
  renders the Prometheus text format, and :func:`serve` scrapes it over
  HTTP.  Each flush also emits every counter/gauge into the Chrome trace
  as ``"ph": "C"`` counter events (``profiler.py``), so one
  ``profile.json`` shows spans and metrics on a shared timeline.

Instrumented layers: ``lowering`` (compile counts/wall-time, lowering
cache), ``executor``/``module`` (step latency, samples/sec, epochs),
``engine`` (dispatch counts, fences, in-flight depth), ``ps``/``kvstore``
(RPC count/bytes/latency per verb, retries, heartbeats, dead nodes),
``parallel.collectives`` (invocations by kind + payload bytes),
``parallel.zero`` (``optimizer_state_bytes_total`` /
``optimizer_state_bytes_per_device`` gauges labeled by train-step
scope — the ZeRO-1 footprint signal), ``quant`` + its call sites
(``quant_weight_bytes`` per serving component, ``quant_scale`` per fp8
site/role, ``quant_amax_rescales_total`` — docs/quantization.md),
``resilience`` (``ckpt_saves_total{mode}``, ``ckpt_save_seconds``,
``ckpt_bytes``, ``ckpt_async_queue_depth``, ``restores_total``,
``ckpt_restore_seconds``, ``ckpt_restore_failures_total``,
``ckpt_gc_total``, ``preemptions_total``, ``faults_injected_total``
— docs/fault_tolerance.md), ``serving.paged`` (``serve_kv_pages_free``
/ ``_used`` / ``_cached`` + ``serve_kv_pool_bytes`` gauges,
``serve_prefix_hits_total``, ``serve_prefix_hit_tokens_total``,
``serve_prefix_evictions_total``, ``serve_kv_cow_total``,
``serve_prefill_tokens_total`` — docs/paged_kv.md), and device memory
via ``jax.local_devices()[*].memory_stats()``.

Env controls::

    TP_TELEMETRY=1            enable the registry
    TP_TELEMETRY_PATH=...     JSONL sink (default telemetry.jsonl)
    TP_TELEMETRY_TRACE=0      suppress the exit-time counter-event trace dump
    TP_TELEMETRY_STEP_FENCE=1 per-step true readback fence in Module.fit
    TP_TELEMETRY_RESERVOIR=N  histogram reservoir size (default 1024)
    TP_TELEMETRY_PORT=N       serve Prometheus text on http://:N/metrics
"""
from __future__ import annotations

import atexit
import json
import random
import threading
import time
from typing import Any, Dict, Optional, Tuple

from .base import get_env

__all__ = [
    "enabled", "enable", "disable", "counter", "gauge", "histogram",
    "snapshot", "flush", "prometheus_text", "serve", "registry",
    "Counter", "Gauge", "Histogram", "Registry",
]


# ---------------------------------------------------------------------------
# no-op singletons (the disabled-mode hot path)
# ---------------------------------------------------------------------------


class _NullTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TIMER = _NullTimer()


class _NullMetric:
    """Shared no-op standing in for every metric when telemetry is off.

    All mutators are allocation-free so per-step instrumentation adds no
    garbage to the hot path (asserted by ``tests/test_telemetry.py``).
    """

    __slots__ = ()

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    def time(self):
        return _NULL_TIMER


_NULL = _NullMetric()


# ---------------------------------------------------------------------------
# metric types
# ---------------------------------------------------------------------------


def _format_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return name
    return "%s{%s}" % (name, ",".join('%s="%s"' % (k, v)
                                      for k, v in labels))


class _Timer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)
        return False


class _Metric:
    __slots__ = ("name", "labels", "_lock")
    kind = "metric"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()

    @property
    def key(self) -> str:
        return _format_key(self.name, self.labels)

    def time(self):
        return _NULL_TIMER


class Counter(_Metric):
    """Monotonic counter (``_total`` convention)."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self._value = 0

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def snap(self) -> Dict[str, Any]:
        return {"type": "counter", "value": self._value}


class Gauge(_Metric):
    """Last-value gauge."""

    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self._value = 0.0

    def set(self, v) -> None:
        self._value = float(v)

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value

    def snap(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self._value}


class Histogram(_Metric):
    """count/sum/min/max plus a bounded reservoir for quantiles.

    The reservoir holds at most ``TP_TELEMETRY_RESERVOIR`` samples
    (default 1024); beyond that, uniform reservoir sampling keeps memory
    bounded for arbitrarily long runs while quantile estimates stay
    representative of the whole stream.
    """

    __slots__ = ("count", "sum", "min", "max", "_reservoir", "_cap")
    kind = "histogram"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._cap = int(get_env("TELEMETRY_RESERVOIR", 1024, int))
        self._reservoir = []

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._reservoir) < self._cap:
                self._reservoir.append(v)
            else:
                i = random.randrange(self.count)
                if i < self._cap:
                    self._reservoir[i] = v

    def time(self):
        return _Timer(self)

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            res = sorted(self._reservoir)
        if not res:
            return None
        idx = min(len(res) - 1, int(q * len(res)))
        return res[idx]

    def snap(self) -> Dict[str, Any]:
        with self._lock:
            res = sorted(self._reservoir)
            out = {"type": "histogram", "count": self.count,
                   "sum": self.sum, "min": self.min, "max": self.max}
        for q in (0.5, 0.9, 0.99):
            if res:
                out["p%d" % int(q * 100)] = \
                    res[min(len(res) - 1, int(q * len(res)))]
        return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class Registry:
    """Process-wide metric store; one instance lives while enabled."""

    def __init__(self, jsonl_path: Optional[str] = None):
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            _Metric] = {}
        self._lock = threading.Lock()
        self.jsonl_path = jsonl_path or get_env("TELEMETRY_PATH",
                                                "telemetry.jsonl")

    def get(self, cls, name: str,
            labels: Optional[Dict[str, str]] = None) -> _Metric:
        lab = tuple(sorted((str(k), str(v))
                           for k, v in labels.items())) if labels else ()
        key = (name, lab)
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, lab)
                    self._metrics[key] = m
        return m

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    # ------------------------------------------------------------ exposition
    def snapshot(self) -> Dict[str, Any]:
        """One point-in-time dict: ``{"ts": ..., "metrics": {key: snap}}``."""
        self.record_device_memory()
        return {"ts": time.time(),
                "metrics": {m.key: m.snap() for m in self.metrics()}}

    def flush(self, path: Optional[str] = None) -> str:
        """Append one snapshot line to the JSONL sink and mirror every
        counter/gauge into the Chrome trace as a ``"ph": "C"`` event."""
        snap = self.snapshot()
        path = path or self.jsonl_path
        with open(path, "a") as f:
            f.write(json.dumps(snap) + "\n")
        self._emit_trace_counters(snap)
        return path

    def _emit_trace_counters(self, snap: Dict[str, Any]) -> None:
        from . import profiler

        for key, s in snap["metrics"].items():
            if s["type"] in ("counter", "gauge"):
                profiler.record_counter(key, s["value"])
            else:  # histogram: count is the useful time series
                profiler.record_counter(key + ".count", s["count"])

    def prometheus_text(self) -> str:
        """Prometheus exposition text format (counters/gauges as-is,
        histograms as summaries with reservoir quantiles)."""
        by_name: Dict[str, list] = {}
        for m in self.metrics():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_name):
            group = by_name[name]
            kind = group[0].kind
            lines.append("# TYPE %s %s" % (
                name, "summary" if kind == "histogram" else kind))
            for m in group:
                if kind == "histogram":
                    for q in (0.5, 0.9, 0.99):
                        v = m.quantile(q)
                        if v is None:
                            continue
                        lab = m.labels + (("quantile", str(q)),)
                        lines.append("%s %g" % (_format_key(name, lab), v))
                    lines.append("%s %g" % (
                        _format_key(name + "_sum", m.labels), m.sum))
                    lines.append("%s %d" % (
                        _format_key(name + "_count", m.labels), m.count))
                else:
                    lines.append("%s %g" % (m.key, m.value))
        return "\n".join(lines) + "\n"

    # --------------------------------------------------------- device memory
    def record_device_memory(self) -> None:
        """Refresh per-device memory gauges from
        ``jax.local_devices()[*].memory_stats()`` (None on backends that
        do not report, e.g. CPU)."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None:  # never force the backend up just to report 0
            return
        try:
            devices = jax.local_devices()
        except Exception:
            return
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            lab = {"device": "%s:%d" % (d.platform, d.id)}
            for stat_key, metric in (
                    ("bytes_in_use", "device_memory_bytes_in_use"),
                    ("peak_bytes_in_use", "device_memory_peak_bytes"),
                    ("bytes_limit", "device_memory_bytes_limit")):
                if stat_key in stats:
                    self.get(Gauge, metric, lab).set(stats[stat_key])


# ---------------------------------------------------------------------------
# module-level state + accessors
# ---------------------------------------------------------------------------

_REG: Optional[Registry] = None
_state_lock = threading.Lock()
_atexit_registered = False


def enabled() -> bool:
    return _REG is not None


def registry() -> Optional[Registry]:
    return _REG


def enable(jsonl_path: Optional[str] = None) -> Registry:
    """Turn the registry on (the in-process spelling of ``TP_TELEMETRY=1``)."""
    global _REG, _atexit_registered
    with _state_lock:
        if _REG is None:
            _REG = Registry(jsonl_path)
        elif jsonl_path:
            _REG.jsonl_path = jsonl_path
        if not _atexit_registered:
            atexit.register(_at_exit)
            _atexit_registered = True
        return _REG


def disable() -> None:
    """Drop the registry; accessors return the no-op singleton again."""
    global _REG
    _REG = None


def counter(name: str, labels: Optional[Dict[str, str]] = None):
    r = _REG
    if r is None:
        return _NULL
    return r.get(Counter, name, labels)


def gauge(name: str, labels: Optional[Dict[str, str]] = None):
    r = _REG
    if r is None:
        return _NULL
    return r.get(Gauge, name, labels)


def histogram(name: str, labels: Optional[Dict[str, str]] = None):
    r = _REG
    if r is None:
        return _NULL
    return r.get(Histogram, name, labels)


def snapshot() -> Optional[Dict[str, Any]]:
    r = _REG
    return r.snapshot() if r is not None else None


def flush(path: Optional[str] = None) -> Optional[str]:
    r = _REG
    return r.flush(path) if r is not None else None


def prometheus_text() -> str:
    r = _REG
    return r.prometheus_text() if r is not None else ""


def serve(port: int = 9464):
    """Serve ``prometheus_text()`` at ``/metrics`` from a daemon thread
    (the Prometheus scrape endpoint).  Returns the HTTPServer."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet scrapes
            pass

    srv = HTTPServer(("0.0.0.0", port), _Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _at_exit() -> None:
    r = _REG
    if r is None:
        return
    try:
        r.flush()
    except OSError:
        return
    if get_env("TELEMETRY_TRACE", True, bool):
        # one profile.json carrying spans AND the counter time series
        from . import profiler

        try:
            profiler.dump_profile()
        except OSError:
            pass


# env gate (the TP_TELEMETRY=1 contract)
if get_env("TELEMETRY", False, bool):
    enable()
    _port = get_env("TELEMETRY_PORT", 0, int)
    if _port:
        serve(_port)
