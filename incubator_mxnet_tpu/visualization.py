"""Network visualization — ``plot_network`` / ``print_summary``.

Reference analog: ``python/mxnet/visualization.py`` (graphviz plot of the
symbol JSON graph + layer-table summary with parameter counts).  Works over
the same Symbol DAG the executor lowers; graphviz is optional (dot source is
always produced, rendering needs the library).
"""
from __future__ import annotations

from typing import Dict, Optional

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _node_label(node) -> str:
    if node.is_variable:
        return node.name
    op = node.op.name
    a = node.attrs
    if op == "Convolution":
        return "Convolution\n%s/%s, %s" % (a.get("kernel"), a.get("stride",
                                                                  "(1,1)"),
                                           a.get("num_filter"))
    if op == "FullyConnected":
        return "FullyConnected\n%s" % a.get("num_hidden")
    if op == "Pooling":
        return "Pooling\n%s, %s/%s" % (a.get("pool_type", "max"),
                                       a.get("kernel"),
                                       a.get("stride", "(1,1)"))
    if op in ("Activation", "LeakyReLU"):
        return "%s\n%s" % (op, a.get("act_type", ""))
    return op


def print_summary(symbol, shape: Optional[Dict] = None,
                  line_length: int = 120,
                  positions=(.44, .64, .74, 1.)) -> None:
    """Layer table: name, output shape, #params, previous layers
    (reference ``print_summary``)."""
    shape_dict = {}
    input_names = set()
    if shape is not None:
        # names the caller feeds (data/label) are inputs, not parameters
        input_names = set(shape.keys())
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(cols, pos):
        line = ""
        for col, p in zip(cols, pos):
            line += str(col)
            line = line[:p].ljust(p)
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total_params = 0

    for node in symbol.topo_nodes():
        if node.is_variable:
            continue
        out_name = node.output_names()[0]
        out_shape = shape_dict.get(out_name, "")
        # parameter count: product of shapes of variable inputs
        n_params = 0
        prev = []
        for inp, _ in node.inputs:
            if inp.is_variable and inp.name not in input_names \
                    and not inp.name.endswith("label"):
                s = shape_dict.get(inp.name)
                if s:
                    p = 1
                    for d in s:
                        p *= d
                    n_params += p
            else:
                prev.append(inp.name)
        total_params += n_params
        print_row(["%s (%s)" % (node.name, node.op.name),
                   out_shape, n_params, ",".join(prev)], positions)
        print("_" * line_length)
    print("Total params: %d" % total_params)
    print("_" * line_length)


def plot_network(symbol, title: str = "plot",
                 shape: Optional[Dict] = None, node_attrs=None,
                 save_format: str = "pdf", hide_weights: bool = True):
    """Graphviz digraph of the symbol (reference ``plot_network``).

    Returns a ``graphviz.Digraph`` if the library is importable, else a
    string of dot source (same graph either way).
    """
    shape_dict = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))

    fill = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
            "BatchNorm": "#bebada", "Activation": "#ffffb3",
            "LeakyReLU": "#ffffb3", "Pooling": "#80b1d3",
            "Concat": "#fdb462", "Flatten": "#fdb462",
            "Reshape": "#fdb462", "SoftmaxOutput": "#b3de69"}

    nodes = symbol.topo_nodes()
    hidden = set()
    if hide_weights:
        for node in nodes:
            if node.op is not None:
                for pos, (inp, _) in enumerate(node.inputs):
                    if inp.is_variable and pos >= 1:
                        hidden.add(id(inp))

    lines = ["digraph %s {" % json_safe(title)]
    for node in nodes:
        if id(node) in hidden:
            continue
        label = _node_label(node).replace("\n", "\\n")
        out_shape = shape_dict.get(node.output_names()[0])
        if out_shape:
            label += "\\n%s" % (tuple(out_shape),)
        color = "#8dd3c7" if node.is_variable else \
            fill.get(node.op.name, "#fccde5")
        lines.append('  "%s" [label="%s", style=filled, fillcolor="%s", '
                     'shape=box];' % (node.name, label, color))
    for node in nodes:
        for inp, _ in node.inputs:
            if id(inp) in hidden:
                continue
            lines.append('  "%s" -> "%s";' % (inp.name, node.name))
    lines.append("}")
    src = "\n".join(lines)

    try:
        from graphviz import Digraph  # type: ignore

        dot = Digraph(name=title, format=save_format)
        # feed pre-built source body
        dot.body = [ln for ln in lines[1:-1]]
        return dot
    except ImportError:
        return src


def json_safe(s: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in s)
