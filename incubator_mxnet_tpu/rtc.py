"""Runtime-compiled kernels — the TPU analog of ``mx.rtc``.

Reference analog: ``python/mxnet/rtc.py`` + ``src/common/mxrtc.cc:26-159``
(NVRTC: compile CUDA C from a python string at runtime, launch with
explicit grid/block).  On TPU the runtime-codegen path is **Pallas**: the
kernel body is python source compiled by Mosaic when first traced, so the
same "write a kernel as a string / function, call it on NDArrays" UX maps
onto ``pl.pallas_call``.

Differences from CUDA RTC, by design:
- the kernel indexes ``Ref`` blocks (``x[...]``) instead of raw threads;
- grid/block become the pallas ``grid`` + per-input ``BlockSpec``;
- on non-TPU backends the kernel runs in interpret mode (the reference's
  RTC was likewise CUDA-only, guarded by ``MXNET_USE_NVRTC``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["Rtc", "PallasKernel"]


def _default_interpret() -> bool:
    import jax

    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


class PallasKernel:
    """Compile a Pallas kernel from python source at runtime.

    ``source`` must define a function named ``name`` taking one ``Ref``
    per input followed by one per output::

        k = PallasKernel("axpy", ["x", "y"], ["out"], '''
        def axpy(x, y, out):
            out[...] = 2.0 * x[...] + y[...]
        ''')
        out = k(x_nd, y_nd)

    The body may use ``pl``/``pltpu``/``jnp``/``jax`` — they are injected
    into the source's namespace (the reference injected CUDA builtins the
    same way by textual wrapping, ``mxrtc.cc:101-135``).
    """

    def __init__(self, name: str, inputs: Sequence[str],
                 outputs: Sequence[str], source: str,
                 grid: Optional[Tuple[int, ...]] = None,
                 interpret: Optional[bool] = None):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        try:
            from jax.experimental.pallas import tpu as pltpu
        except ImportError:  # pragma: no cover
            pltpu = None

        self.name = name
        self.input_names = list(inputs)
        self.output_names = list(outputs)
        self.source = source
        self.grid = grid
        self.interpret = _default_interpret() if interpret is None \
            else interpret

        namespace = {"pl": pl, "pltpu": pltpu, "jnp": jnp, "jax": jax,
                     "np": np}
        exec(compile(source, "<rtc:%s>" % name, "exec"), namespace)
        if name not in namespace or not callable(namespace[name]):
            raise MXNetError(
                "rtc source must define a function named '%s'" % name)
        self._kernel = namespace[name]
        self._pl = pl

    def _call_arrays(self, ins, out_shape_dtypes):
        import jax

        pl = self._pl
        call = pl.pallas_call(
            self._kernel,
            out_shape=[jax.ShapeDtypeStruct(s, d)
                       for s, d in out_shape_dtypes],
            grid=self.grid if self.grid is not None else (),
            interpret=self.interpret)
        outs = call(*ins)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        return list(outs)

    def push(self, ins: Sequence[NDArray], outs: Sequence[NDArray],
             grid_dims=None, block_dims=None) -> None:
        """Reference-shaped launch API (``mx.rtc.push``): writes results
        into ``outs``.  grid/block dims are accepted for signature parity;
        pallas derives its own tiling."""
        results = self._call_arrays(
            [i.data for i in ins],
            [(tuple(o.shape), o.dtype) for o in outs])
        for o, r in zip(outs, results):
            o[:] = np.asarray(r)

    def __call__(self, *ins, out_shapes=None, out_dtypes=None):
        """Functional form: returns new NDArrays (out shapes default to
        the first input's)."""
        arrays = [i.data if isinstance(i, NDArray) else i for i in ins]
        if out_shapes is None:
            out_shapes = [tuple(arrays[0].shape)] * len(self.output_names)
        if out_dtypes is None:
            out_dtypes = [arrays[0].dtype] * len(self.output_names)
        results = self._call_arrays(arrays,
                                    list(zip(out_shapes, out_dtypes)))
        from .ndarray import array as nd_array

        outs = [nd_array(np.asarray(r)) for r in results]
        if len(outs) == 1:
            return outs[0]
        return outs


class Rtc(PallasKernel):
    """Name-compatible alias of the reference ``mx.rtc.Rtc``; same
    constructor ordering (name, inputs, outputs, kernel_source)."""

    def __init__(self, name, inputs, outputs, kernel):
        super().__init__(name, inputs, outputs, kernel)
