"""Image io + augmentation — ``mx.image``.

Reference analog: ``python/mxnet/image/image.py`` (imread :44, imdecode
:85, crop/resize helpers :139-480, Augmenter zoo :482-860,
CreateAugmenter :861, ImageIter :975) and the C++ ``ImageRecordIter``
pipeline it mirrors (``src/io/iter_image_recordio_2.cc``).

TPU-native note: decode/augment is deliberately HOST-side numpy/OpenCV
work — on a TPU system the input pipeline runs on the host CPU and only
device-ready batches cross PCIe, exactly like the reference's
multithreaded OpenCV parser fed pinned buffers to the GPU.  Augmented
arrays stay numpy until batch assembly; the batch is one device_put.
"""
from __future__ import annotations

import json
import logging
import os
import random as pyrandom
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import io as io_mod
from .. import ndarray as nd
from .. import recordio
from ..base import MXNetError

try:
    import cv2
except ImportError:  # pragma: no cover - cv2 is in the image
    cv2 = None

__all__ = ["imdecode", "imread", "imresize", "scale_down", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "Augmenter", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "RandomSizedCropAug", "CenterCropAug",
           "RandomOrderAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
           "LightingAug", "ColorNormalizeAug", "RandomGrayAug",
           "HorizontalFlipAug", "CastAug", "CreateAugmenter", "ImageIter"]


def _require_cv2():
    if cv2 is None:
        raise MXNetError("OpenCV (cv2) is required for mx.image")


def _wrap_like(src, out):
    """NDArray in → NDArray out; plain numpy in → numpy out.

    Augmenter math is all cv2/numpy; wrapping every intermediate in an
    NDArray would round-trip each image through the accelerator once per
    augmenter step.  Iterators therefore feed numpy through the chain
    and only the final assembled batch becomes an NDArray."""
    if isinstance(src, np.ndarray):
        return out
    return nd.array(out)


def _imdecode_np(buf, flag=1, to_rgb=True):
    """Decode an image byte buffer to an HWC uint8 numpy array."""
    _require_cv2()
    img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8),
                       cv2.IMREAD_COLOR if flag else
                       cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("cannot decode image")
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to HWC ndarray (reference
    ``image.py:85``; BGR→RGB like the reference's default)."""
    return nd.array(_imdecode_np(buf, flag=flag, to_rgb=to_rgb))


def imread(filename, flag=1, to_rgb=True):
    """Read + decode an image file (reference ``image.py:44``)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=2):
    """Resize to exactly (w, h)."""
    _require_cv2()
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    out = cv2.resize(arr, (w, h),
                     interpolation=_get_interp_method(interp))
    if out.ndim == 2:
        out = out[:, :, None]
    return _wrap_like(src, out)


def scale_down(src_size, size):
    """Scale requested crop size down to fit the source
    (reference ``image.py:139``)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def _get_interp_method(interp, sizes=()):
    """Interp code → cv2 constant; 9=auto by scale, 10=random
    (reference ``image.py:174``)."""
    _require_cv2()
    table = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
             2: cv2.INTER_AREA, 3: cv2.INTER_CUBIC,
             4: cv2.INTER_LANCZOS4}
    if interp == 9:
        if sizes:
            oh, ow, nh, nw = sizes
            if nh > oh and nw > ow:
                return table[2]
            if nh < oh and nw < ow:
                return table[3]
            return table[1]
        return table[2]
    if interp == 10:
        return table[pyrandom.randint(0, 4)]
    if interp not in table:
        raise MXNetError("unknown interpolation method %s" % interp)
    return table[interp]


def resize_short(src, size, interp=2):
    """Resize the shorter edge to ``size`` (reference ``image.py:229``)."""
    _require_cv2()
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    out = cv2.resize(arr, (new_w, new_h), interpolation=_get_interp_method(
        interp, (h, w, new_h, new_w)))
    if out.ndim == 2:
        out = out[:, :, None]
    return _wrap_like(src, out)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop a fixed region, optionally resizing to ``size``
    (reference ``image.py:291``)."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = cv2.resize(out, size, interpolation=_get_interp_method(
            interp, (h, w, size[1], size[0])))
        if out.ndim == 2:
            out = out[:, :, None]
    return _wrap_like(src, out)


def random_crop(src, size, interp=2):
    """Random crop of ``size`` (scaled down if needed); returns
    (cropped, (x0, y0, w, h)) (reference ``image.py:323``)."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop (reference ``image.py:362``)."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(src - mean) / std (reference ``image.py:411``)."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    arr = arr.astype(np.float32)
    if mean is not None:
        arr = arr - np.asarray(mean, dtype=np.float32)
    if std is not None:
        arr = arr / np.asarray(std, dtype=np.float32)
    return _wrap_like(src, arr)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area+aspect crop (Inception-style)
    (reference ``image.py:435``)."""
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    h, w = arr.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = pyrandom.uniform(min_area, 1.0) * area
        new_ratio = pyrandom.uniform(*ratio)
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if pyrandom.random() < 0.5:
            new_h, new_w = new_w, new_h
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


# ---------------------------------------------------------------------------
# Augmenters
# ---------------------------------------------------------------------------


class Augmenter(object):
    """Image augmenter base (reference ``image.py:482``)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in self._kwargs.items():
            if isinstance(v, nd.NDArray):
                self._kwargs[k] = v.asnumpy().tolist()
            elif isinstance(v, np.ndarray):
                self._kwargs[k] = v.tolist()

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [resize_short(src, self.size, self.interp)]


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [imresize(src, self.size[0], self.size[1], self.interp)]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [random_crop(src, self.size, self.interp)[0]]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size = size
        self.min_area = min_area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return [random_size_crop(src, self.size, self.min_area,
                                 self.ratio, self.interp)[0]]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [center_crop(src, self.size, self.interp)[0]]


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def dumps(self):
        return [self.__class__.__name__.lower(),
                [x.dumps() for x in self.ts]]

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        srcs = [src]
        for t in ts:
            srcs = [img for s in srcs for img in t(s)]
        return srcs


def _jitter(src, alpha, mode):
    arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
    arr = arr.astype(np.float32)
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)
    if mode == "brightness":
        arr *= alpha
    elif mode == "contrast":
        gray = (arr * coef).sum(axis=2, keepdims=True)
        arr = arr * alpha + gray.mean() * (1.0 - alpha)
    elif mode == "saturation":
        gray = (arr * coef).sum(axis=2, keepdims=True)
        arr = arr * alpha + gray * (1.0 - alpha)
    return _wrap_like(src, arr)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return [_jitter(src, alpha, "brightness")]


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        return [_jitter(src, alpha, "contrast")]


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        return [_jitter(src, alpha, "saturation")]


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], dtype=np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], dtype=np.float32)

    def __call__(self, src):
        arr = src.asnumpy() if hasattr(src, "asnumpy") \
            else np.asarray(src)
        arr = arr.astype(np.float32)
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      dtype=np.float32)
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        return [_wrap_like(src, np.dot(arr, t))]


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-based lighting noise (reference ``image.py`` LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        arr = src.asnumpy() if hasattr(src, "asnumpy") \
            else np.asarray(src)
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return [_wrap_like(src, arr.astype(np.float32) + rgb)]


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = np.asarray(mean, np.float32) \
            if mean is not None else None
        self.std = np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src):
        return [color_normalize(src, self.mean, self.std)]


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = np.array([[0.21, 0.21, 0.21],
                             [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]], dtype=np.float32)

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = src.asnumpy() if hasattr(src, "asnumpy") \
                else np.asarray(src)
            src = _wrap_like(src, np.dot(arr.astype(np.float32),
                                         self.mat))
        return [src]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = src.asnumpy() if hasattr(src, "asnumpy") \
                else np.asarray(src)
            src = _wrap_like(src, arr[:, ::-1].copy())
        return [src]


class CastAug(Augmenter):
    def __init__(self):
        super().__init__(type="float32")

    def __call__(self, src):
        arr = src.asnumpy() if hasattr(src, "asnumpy") \
            else np.asarray(src)
        return [_wrap_like(src, arr.astype(np.float32))]


def CreateAugmenter(data_shape, resize=0, rand_crop=False,
                    rand_resize=False, rand_mirror=False, mean=None,
                    std=None, brightness=0, contrast=0, saturation=0,
                    hue=0, pca_noise=0, rand_gray=0, inter_method=2,
                    cast=True):
    """Standard augmenter list (reference ``image.py:861``).

    ``cast=False`` builds a uint8-transport chain (crop/resize/flip only;
    no float cast, no host-side color math) — the ImageRecordUInt8Iter
    configuration where normalization belongs on the device."""
    if not cast:
        if mean is not None or std is not None or (
                brightness or contrast or saturation or hue or pca_noise
                or rand_gray):
            raise MXNetError(
                "cast=False keeps color math off the host pipeline; "
                "mean/std/jitter arguments would be silently dropped")
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0,
                                                            4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if not cast:
        return auglist
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(io_mod.DataIter):
    """Image iterator with pluggable augmenters, reading ``.rec`` packs
    or an image list + root dir (reference ``image.py:975``)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0,
                 num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or (isinstance(imglist, list))
        if path_imgrec:
            if path_imgidx:
                self.imgrec = recordio.IndexedRecordIO(path_imgidx,
                                                       path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        else:
            self.imgrec = None
            self.imgidx = None

        self.imglist = None
        if path_imglist:
            imglist = {}
            imgkeys = []
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = np.array(
                        [float(i) for i in parts[1:-1]], dtype=np.float32)
                    key = int(parts[0])
                    imglist[key] = (label, parts[-1])
                    imgkeys.append(key)
            self.imglist = imglist
            self.seq = imgkeys
        elif isinstance(imglist, list):
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                if isinstance(img[0], (list, np.ndarray)):
                    label = np.array(img[0], dtype=np.float32)
                else:
                    label = np.array([img[0]], dtype=np.float32)
                result[key] = (label, img[1])
                imgkeys.append(str(key))
            self.imglist = result
            self.seq = imgkeys
        elif self.imgidx is not None:
            self.seq = self.imgidx
        else:
            self.seq = None

        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            N = len(self.seq)
            C = N // num_parts
            self.seq = self.seq[part_index * C:(part_index + 1) * C]

        self.path_root = path_root
        assert len(data_shape) == 3 and data_shape[0] in (1, 3), \
            "data_shape must be (c, h, w) with c in {1, 3}, got %s" \
            % (data_shape,)
        self.provide_data = [io_mod.DataDesc(data_name,
                                             (batch_size,) + data_shape)]
        if label_width > 1:
            self.provide_label = [io_mod.DataDesc(
                label_name, (batch_size, label_width))]
        else:
            self.provide_label = [io_mod.DataDesc(label_name,
                                                  (batch_size,))]
        self.data_shape = data_shape
        self.label_width = label_width
        self.shuffle = shuffle
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """Next (label, decoded image) pair."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                # a user-supplied .lst relabels the record (reference
                # image.py next_sample: imglist label wins over header)
                if self.imglist is not None:
                    return self.imglist[idx][0], img
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                img = f.read()
            return label, img
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, c, h, w), dtype=np.float32)
        label_shape = (batch_size, self.label_width) \
            if self.label_width > 1 else (batch_size,)
        batch_label = np.zeros(label_shape, dtype=np.float32)
        i = 0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                data = [_imdecode_np(s)]
                if data[0].shape[0] < self.data_shape[1] and \
                        not self.auglist:
                    raise MXNetError("image smaller than data_shape")
                for aug in self.auglist:
                    data = [ret for src in data for ret in aug(src)]
                for d in data:
                    if i >= batch_size:
                        break
                    arr = d.asnumpy() if hasattr(d, "asnumpy") \
                        else np.asarray(d)
                    batch_data[i] = arr.transpose(2, 0, 1)
                    if self.label_width > 1:
                        batch_label[i] = np.asarray(label)[
                            :self.label_width]
                    else:
                        batch_label[i] = np.asarray(label).reshape(-1)[0]
                    i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = batch_size - i
        return io_mod.DataBatch([nd.array(batch_data)],
                                [nd.array(batch_label)], pad=pad,
                                provide_data=self.provide_data,
                                provide_label=self.provide_label)

    __next__ = next
