"""``mx.image`` — host-side image decode/augment pipeline
(reference ``python/mxnet/image/image.py``)."""
from .image import (Augmenter, BrightnessJitterAug, CastAug,
                    CenterCropAug, ColorJitterAug, ColorNormalizeAug,
                    ContrastJitterAug, CreateAugmenter, ForceResizeAug,
                    HorizontalFlipAug, HueJitterAug, ImageIter,
                    LightingAug, RandomCropAug, RandomGrayAug,
                    RandomOrderAug, RandomSizedCropAug, ResizeAug,
                    SaturationJitterAug, center_crop, color_normalize,
                    fixed_crop, imdecode, imread, imresize, random_crop,
                    random_size_crop, resize_short, scale_down)
from .detection import (DetAugmenter, DetBorrowAug, DetRandomSelectAug,
                        DetHorizontalFlipAug, DetRandomCropAug,
                        DetRandomPadAug, CreateMultiRandCropAugmenter,
                        CreateDetAugmenter, ImageDetIter)
from . import detection as det
