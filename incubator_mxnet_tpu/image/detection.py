"""Detection image iterator + box-aware augmenters.

Reference analogs: ``python/mxnet/image/detection.py`` (ImageDetIter,
CreateDetAugmenter, the DetAugmenter family) and the C++ det pipeline
(``src/io/iter_image_det_recordio.cc:596``, ``image_det_aug_default.cc``).

Label wire format (image_det_aug_default.cc:248-281 ``ImageDetLabel``):
``[header_width, object_width, <extra header...>,
(id, xmin, ymin, xmax, ymax, <extra...>) * N]`` with normalized [0,1]
corner coordinates.  Batched labels are padded with -1 rows to the
estimated max object count, which is what ``_contrib_MultiBoxTarget``
consumes.
"""
from __future__ import annotations

import logging
import random as pyrandom
from math import sqrt

import numpy as np

from .. import io as io_mod
from .. import ndarray as nd
from ..base import MXNetError
from . import image as image_mod
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    ForceResizeAug, HueJitterAug, LightingAug, RandomGrayAug,
                    ResizeAug, fixed_crop, imdecode, ImageIter)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


def _box_areas(boxes):
    """Areas of normalized (N, 4) corner boxes, degenerate -> 0."""
    return (np.maximum(0, boxes[:, 2] - boxes[:, 0])
            * np.maximum(0, boxes[:, 3] - boxes[:, 1]))


def _to_np(src):
    """Coerce NDArray/array-like to a host numpy HWC image.  The pad/flip
    augmenters do raw numpy indexing; feeding them a device NDArray would
    fall into numpy's element-wise iteration path."""
    return src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)


class DetAugmenter(object):
    """Base detection augmenter: ``(image, label) -> (image, label)``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift a plain (image-only) augmenter into the detection chain."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps()
                         if isinstance(augmenter, Augmenter) else str(augmenter))
        self.augmenter = augmenter

    def __call__(self, src, label):
        out = self.augmenter(src)
        if isinstance(out, (list, tuple)):
            out = out[0]
        return out, label


class DetRandomSelectAug(DetAugmenter):
    """Randomly run one augmenter from a list (or none with skip_prob)."""

    def __init__(self, aug_list, skip_prob=0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image + labels with probability p."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = _to_np(src)[:, ::-1]
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (image/detection.py:150-320 semantics):
    sample a crop satisfying aspect/area constraints and
    ``min_object_covered``; project labels into the crop and eject objects
    whose surviving area fraction is below ``min_eject_coverage``."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.enabled = (0 < area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1]
                        and area_range[1] > 0)

    def _project(self, label, x, y, w, h, height, width):
        """Labels into normalized crop coords; None if all ejected."""
        nx, ny = x / width, y / height
        nw, nh = w / width, h / height
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] - nx) / nw
        out[:, (2, 4)] = (out[:, (2, 4)] - ny) / nh
        out[:, 1:5] = np.clip(out[:, 1:5], 0.0, 1.0)
        old_area = _box_areas(label[:, 1:5])
        new_area = _box_areas(out[:, 1:5]) * nw * nh
        with np.errstate(divide="ignore", invalid="ignore"):
            coverage = np.where(old_area > 0, new_area / old_area, 0.0)
        keep = ((out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
                & (coverage > self.min_eject_coverage))
        if not keep.any():
            return None
        return out[keep]

    def _satisfies(self, label, x, y, w, h, height, width):
        if w * h < 2:
            return False
        x1, y1 = x / width, y / height
        x2, y2 = (x + w) / width, (y + h) / height
        areas = _box_areas(label[:, 1:5])
        valid = areas * width * height > 2
        if not valid.any():
            return False
        b = label[valid, 1:5]
        il = np.maximum(b[:, 0], x1)
        it = np.maximum(b[:, 1], y1)
        ir = np.minimum(b[:, 2], x2)
        ib = np.minimum(b[:, 3], y2)
        inter = np.where((il < ir) & (it < ib), (ir - il) * (ib - it), 0.0)
        cov = inter / areas[valid]
        cov = cov[cov > 0]
        return cov.size > 0 and cov.min() > self.min_object_covered

    def __call__(self, src, label):
        height, width = src.shape[:2]
        if not self.enabled or height <= 0 or width <= 0:
            return src, label
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = min(int(round(sqrt(max_area / ratio))),
                        int(width / ratio), height)
            if h > max_h:
                h = max_h
            if h < max_h:
                h = pyrandom.randint(h, max_h)
            w = int(round(h * ratio))
            if (w * h < min_area or w * h > max_area or w > width
                    or h > height or w <= 0 or h <= 0):
                continue
            y = pyrandom.randint(0, max(0, height - h))
            x = pyrandom.randint(0, max(0, width - w))
            if self._satisfies(label, x, y, w, h, height, width):
                new_label = self._project(label, x, y, w, h, height, width)
                if new_label is not None:
                    return fixed_crop(src, x, y, w, h, None), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding: place the image inside a larger canvas
    filled with ``pad_val`` and rescale labels accordingly."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (list, tuple)):
            pad_val = (pad_val,) * 3
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0
                        and area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        src = _to_np(src)
        height, width = src.shape[:2]
        if not self.enabled or height <= 0 or width <= 0:
            return src, label
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h = int(round(sqrt(min_area / ratio)))
            max_h = int(round(sqrt(max_area / ratio)))
            if round(h * ratio) < width:
                h = int((width + 0.499999) / ratio)
            h = min(max(h, height), max_h)
            if h < max_h:
                h = pyrandom.randint(h, max_h)
            w = int(round(h * ratio))
            if (h - height) < 2 or (w - width) < 2:
                continue
            y = pyrandom.randint(0, max(0, h - height))
            x = pyrandom.randint(0, max(0, w - width))
            canvas = np.empty((h, w, src.shape[2]), dtype=src.dtype)
            canvas[:] = np.asarray(self.pad_val, dtype=src.dtype)
            canvas[y:y + height, x:x + width] = src
            out = label.copy()
            out[:, (1, 3)] = (out[:, (1, 3)] * width + x) / w
            out[:, (2, 4)] = (out[:, (2, 4)] * height + y) / h
            return canvas, out
        return src, label


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0):
    """Bundle several DetRandomCropAug variants behind one random select
    (image/detection.py:417-480); scalar params broadcast to the longest
    list."""
    params = [min_object_covered, aspect_ratio_range, area_range,
              min_eject_coverage, max_attempts]
    lists = [p if isinstance(p, list) else [p] for p in params]
    n = max(len(p) for p in lists)
    lists = [p * n if len(p) == 1 else p for p in lists]
    for p in lists:
        assert len(p) == n, "parameter list length mismatch"
    augs = [DetRandomCropAug(min_object_covered=moc, aspect_ratio_range=arr,
                             area_range=ar, min_eject_coverage=mec,
                             max_attempts=ma)
            for moc, arr, ar, mec, ma in zip(*lists)]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Standard SSD augmentation chain (image/detection.py:482-622)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(1.0, area_range[1])), min_eject_coverage,
            max_attempts, skip_prob=(1 - rand_crop)))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        auglist.append(DetRandomSelectAug(
            [DetRandomPadAug(aspect_ratio_range, (1.0, area_range[1]),
                             max_attempts, pad_val)], 1 - rand_pad))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator producing (B, C, H, W) images and padded
    (B, max_objects, object_width) labels (image/detection.py:624).

    Unlabeled slots are filled with -1, the convention
    ``_contrib_MultiBoxTarget`` expects for padded ground truths.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        if aug_list is None:
            self.auglist = CreateDetAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        label_shape = self._estimate_label_shape()
        self.label_name = label_name
        self.label_shape = label_shape
        self.provide_label = [io_mod.DataDesc(
            label_name, (self.batch_size,) + label_shape)]

    # --- label plumbing ---------------------------------------------------
    def _parse_label(self, label):
        """Raw header+objects array -> (N, object_width) valid objects."""
        raw = np.asarray(label).ravel()
        if raw.size < 7:
            raise MXNetError("Label shape is invalid: %s" % (raw.shape,))
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if (raw.size - header_width) % obj_width != 0:
            raise MXNetError(
                "Label shape %s inconsistent with annotation width %d"
                % (raw.shape, obj_width))
        out = raw[header_width:].reshape(-1, obj_width)
        valid = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
        if not valid.any():
            raise MXNetError("Encountered sample with no valid label.")
        return out[valid].astype(np.float32)

    def _check_valid_label(self, label):
        if label.ndim != 2 or label.shape[1] < 5:
            raise MXNetError("Label with shape (1+, 5+) required, got %s"
                             % (label.shape,))
        ok = ((label[:, 0] >= 0) & (label[:, 3] > label[:, 1])
              & (label[:, 4] > label[:, 2]))
        if not ok.any():
            raise MXNetError("Invalid label occurs.")

    def _estimate_label_shape(self):
        max_count, width = 0, 5
        self.reset()
        try:
            while True:
                label, _ = self.next_sample()
                label = self._parse_label(label)
                max_count = max(max_count, label.shape[0])
                width = label.shape[1]
        except StopIteration:
            pass
        self.reset()
        return (max_count, width)

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.provide_data = [io_mod.DataDesc(
                self.provide_data[0].name, (self.batch_size,) + data_shape)]
            self.data_shape = data_shape
        if label_shape is not None:
            self.check_label_shape(label_shape)
            self.provide_label = [io_mod.DataDesc(
                self.provide_label[0].name,
                (self.batch_size,) + label_shape)]
            self.label_shape = label_shape

    def check_label_shape(self, label_shape):
        if len(label_shape) != 2:
            raise ValueError("label_shape should have length 2")
        if label_shape[0] < self.label_shape[0]:
            raise ValueError(
                "Attempts to reduce label count from %d to %d, not allowed"
                % (self.label_shape[0], label_shape[0]))
        if label_shape[1] != self.label_shape[1]:
            raise ValueError("label_shape object width inconsistent: "
                             "%d vs %d" % (self.label_shape[1],
                                           label_shape[1]))

    def sync_label_shape(self, it, verbose=False):
        """Grow both iterators' label pads to the common max object count."""
        assert isinstance(it, ImageDetIter)
        assert self.label_shape[1] == it.label_shape[1], \
            "object width mismatch"
        max_count = max(self.label_shape[0], it.label_shape[0])
        if max_count > self.label_shape[0]:
            self.reshape(None, (max_count, self.label_shape[1]))
        if max_count > it.label_shape[0]:
            it.reshape(None, (max_count, it.label_shape[1]))
        if verbose:
            logging.info("Resized label_shape to (%d, %d).", max_count,
                         self.label_shape[1])
        return it

    def augmentation_transform(self, data, label):
        for aug in self.auglist:
            data, label = aug(data, label)
        return data, label

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, c, h, w), dtype=np.float32)
        batch_label = np.full((batch_size,) + self.label_shape, -1.0,
                              dtype=np.float32)
        i = 0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                # numpy through the augmenter chain (image._wrap_like):
                # no per-image device transfers on the host pipeline
                data = image_mod._imdecode_np(s)
                try:
                    label = self._parse_label(label)
                    data, label = self.augmentation_transform(data, label)
                    self._check_valid_label(label)
                except MXNetError as e:
                    logging.debug("Invalid sample, skipping: %s", e)
                    continue
                arr = data.asnumpy() if hasattr(data, "asnumpy") \
                    else np.asarray(data)
                batch_data[i] = arr.transpose(2, 0, 1)
                num_obj = min(label.shape[0], self.label_shape[0])
                batch_label[i, :num_obj] = label[:num_obj]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return io_mod.DataBatch([nd.array(batch_data)],
                                [nd.array(batch_label)],
                                pad=batch_size - i,
                                provide_data=self.provide_data,
                                provide_label=self.provide_label)

    __next__ = next
