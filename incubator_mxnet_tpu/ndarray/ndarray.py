"""NDArray — the imperative tensor.

Reference analog: ``NDArray`` (``include/mxnet/ndarray.h:77``,
``src/ndarray/ndarray.cc``): a ref-counted async tensor whose mutations are
engine ops.  TPU-native redesign: wraps an immutable ``jax.Array``; "mutation"
rebinds the wrapper (functional update), which composes with JAX async
dispatch exactly like engine write-deps composed with CUDA streams.  Views
(``Slice/At/Reshape`` share storage in the reference, ``ndarray.h:156-172``)
are write-through proxies onto their base array.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from ..engine import engine

__all__ = ["NDArray", "array", "empty", "waitall"]


def _jax():
    import jax

    return jax


class NDArray:
    """Imperative tensor on a device context."""

    __slots__ = ("_data", "_base", "_viewspec", "_ctx", "grad", "_grad_req",
                 "_ag_entry", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None,
                 _base: "NDArray" = None, _viewspec=None):
        self._base = _base
        self._viewspec = _viewspec
        self._ctx = ctx
        self.grad: Optional["NDArray"] = None
        self._grad_req = "null"
        self._ag_entry = None
        if _base is None:
            self._data = data
        else:
            self._data = None

    # ------------------------------------------------------------------ data
    @property
    def data(self):
        """The underlying jax.Array (view-aware read)."""
        if self._base is None:
            return self._data
        kind, spec = self._viewspec
        base = self._base.data
        if kind == "index":
            return base[spec]
        if kind == "reshape":
            return base.reshape(spec)
        raise MXNetError("bad viewspec")

    def _set_data(self, value) -> None:
        """Write-through functional mutation (engine write-dep analog)."""
        if self._base is None:
            self._data = value
            return
        kind, spec = self._viewspec
        base = self._base
        if kind == "index":
            import jax.numpy as jnp

            base._set_data(base.data.at[spec].set(
                jnp.asarray(value, dtype=base.data.dtype)))
        elif kind == "reshape":
            base._set_data(value.reshape(base.data.shape))
        else:
            raise MXNetError("bad viewspec")

    # ------------------------------------------------------------------ meta
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return np.dtype(self.data.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        dev = None
        try:
            devs = self.data.devices()
            dev = next(iter(devs))
        except Exception:
            pass
        if dev is not None and dev.platform != "cpu":
            return Context("tpu", dev.id)
        return Context("cpu", dev.id if dev is not None else 0)

    ctx = context

    def __len__(self) -> int:
        return self.shape[0]

    def __repr__(self) -> str:
        return "%s\n<NDArray %s @%s>" % (
            str(self.asnumpy()), "x".join(str(s) for s in self.shape),
            self.context)

    # ------------------------------------------------------------- transfers
    def asnumpy(self) -> np.ndarray:
        """Blocking copy to host (``WaitToRead`` + copy,
        ``MXNDArraySyncCopyToCPU``)."""
        return np.asarray(self.data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("asscalar requires size-1 array")
        return self.asnumpy().reshape(-1)[0]

    def item(self):
        return self.asscalar()

    def astype(self, dtype) -> "NDArray":
        return NDArray(self.data.astype(dtype_np(dtype)), ctx=self._ctx)

    def copy(self) -> "NDArray":
        return NDArray(_jax().numpy.array(self.data), ctx=self._ctx)

    def copyto(self, other) -> "NDArray":
        """Copy to another NDArray (in-place write) or Context (new array)."""
        if isinstance(other, Context):
            return NDArray(_jax().device_put(self.data, other.jax_device),
                           ctx=other)
        if isinstance(other, NDArray):
            dev = other.context.jax_device
            other._set_data(_jax().device_put(
                self.data.astype(other.data.dtype).reshape(other.shape), dev))
            return other
        raise MXNetError("copyto target must be NDArray or Context")

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    def wait_to_read(self) -> None:
        engine().wait_for_var(self.data)

    def wait_to_write(self) -> None:
        engine().wait_for_var(self.data)

    # ------------------------------------------------------------- reshaping
    @staticmethod
    def _recording() -> bool:
        from .. import autograd

        return autograd.is_recording()

    def reshape(self, *shape) -> "NDArray":
        """Storage-sharing reshape view (``NDArray::Reshape``).  Under
        autograd recording this routes through the Reshape op so the tape
        sees it (the reference records reshape as an op too)."""
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if self._recording():
            from . import op_invoke

            return op_invoke("Reshape", [self], {"shape": shape})
        from ..ops.matrix import _infer_reshape

        tgt = _infer_reshape(self.shape, shape)
        if self._base is None:
            return NDArray(None, ctx=self._ctx, _base=self,
                           _viewspec=("reshape", tgt))
        return NDArray(self.data.reshape(tgt), ctx=self._ctx)

    def expand_dims(self, axis: int) -> "NDArray":
        from . import op_invoke

        return op_invoke("expand_dims", [self], {"axis": axis})

    @property
    def T(self) -> "NDArray":
        from . import op_invoke

        return op_invoke("transpose", [self])

    def flatten(self) -> "NDArray":
        from . import op_invoke

        return op_invoke("Flatten", [self])

    # -------------------------------------------------------------- indexing
    def __getitem__(self, key) -> "NDArray":
        if self._recording() and isinstance(key, (int, slice)):
            # route through slice ops so the tape records the dependency
            from . import op_invoke

            if isinstance(key, int):
                row = op_invoke("slice_axis", [self],
                                {"axis": 0, "begin": key, "end": key + 1})
                return op_invoke("Reshape", [row],
                                 {"shape": self.shape[1:] or (1,)})
            return op_invoke("slice_axis", [self],
                             {"axis": 0, "begin": key.start or 0,
                              "end": key.stop})
        if isinstance(key, int):
            # At(): write-through view of row `key`
            if self._base is None:
                return NDArray(None, ctx=self._ctx, _base=self,
                               _viewspec=("index", key))
            return NDArray(self.data[key], ctx=self._ctx)
        if isinstance(key, slice):
            if key.step is None or key.step == 1:
                if self._base is None:
                    return NDArray(None, ctx=self._ctx, _base=self,
                                   _viewspec=("index", key))
            return NDArray(self.data[key], ctx=self._ctx)
        if isinstance(key, NDArray):
            return NDArray(self.data[key.data.astype("int32")], ctx=self._ctx)
        return NDArray(self.data[key], ctx=self._ctx)

    def __setitem__(self, key, value) -> None:
        import jax.numpy as jnp

        if isinstance(value, NDArray):
            value = value.data
        if isinstance(key, slice) and key == slice(None) \
                and (isinstance(value, (int, float))
                     or (isinstance(value, np.ndarray)
                         and tuple(value.shape) == tuple(self.shape))):
            # full-buffer host assignment (array OR scalar fill) lands
            # straight on THIS array's device: jnp.asarray/jnp.full
            # would materialize on the DEFAULT device — a per-shape
            # compile over the tunnel plus a migration through the
            # ~5 MB/s D2H path for any other ctx.  The initializer
            # zoo's `arr[:] = 0.0` BN fills alone cost ~20 s of
            # round-trips per ResNet-50 before this (PERF.md §1)
            import jax

            host = np.full(self.shape, value,
                           np.dtype(self.data.dtype)) \
                if isinstance(value, (int, float)) \
                else np.asarray(value, dtype=np.dtype(self.data.dtype))
            self._set_data(jax.device_put(
                host,
                self._ctx.jax_device if self._ctx is not None else None))
            return
        if isinstance(value, (int, float)):
            pass
        else:
            value = jnp.asarray(value, dtype=self.data.dtype)
        if isinstance(key, slice) and key == slice(None):
            if np.isscalar(value):
                self._set_data(jnp.full(self.shape, value,
                                        dtype=self.data.dtype))
            else:
                self._set_data(jnp.broadcast_to(value, self.shape).astype(
                    self.data.dtype))
            return
        if isinstance(key, NDArray):
            key = key.data.astype("int32")
        self._set_data(self.data.at[key].set(value))

    # ------------------------------------------------------------ arithmetic
    def _binary(self, other, opname, rop=False):
        from . import op_invoke

        if isinstance(other, NDArray):
            return op_invoke(opname, [self, other])
        scalar_ops = {
            "elemwise_add": "_plus_scalar",
            "elemwise_sub": "_rminus_scalar" if rop else "_minus_scalar",
            "elemwise_mul": "_mul_scalar",
            "elemwise_div": "_rdiv_scalar" if rop else "_div_scalar",
            "_mod": "_rmod_scalar" if rop else "_mod_scalar",
            "_power": "_rpower_scalar" if rop else "_power_scalar",
            "_equal": "_equal_scalar", "_not_equal": "_not_equal_scalar",
            "_greater": "_lesser_scalar" if rop else "_greater_scalar",
            "_greater_equal": "_lesser_equal_scalar" if rop else "_greater_equal_scalar",
            "_lesser": "_greater_scalar" if rop else "_lesser_scalar",
            "_lesser_equal": "_greater_equal_scalar" if rop else "_lesser_equal_scalar",
            "_maximum": "_maximum_scalar", "_minimum": "_minimum_scalar",
        }
        return op_invoke(scalar_ops[opname], [self], {"scalar": other})

    def __add__(self, o):
        return self._binary(o, "elemwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elemwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elemwise_sub", rop=True)

    def __mul__(self, o):
        return self._binary(o, "elemwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elemwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elemwise_div", rop=True)

    def __mod__(self, o):
        return self._binary(o, "_mod")

    def __rmod__(self, o):
        return self._binary(o, "_mod", rop=True)

    def __pow__(self, o):
        return self._binary(o, "_power")

    def __rpow__(self, o):
        return self._binary(o, "_power", rop=True)

    def __neg__(self):
        from . import op_invoke

        return op_invoke("negative", [self])

    def __abs__(self):
        from . import op_invoke

        return op_invoke("abs", [self])

    def __eq__(self, o):
        return self._binary(o, "_equal")

    def __ne__(self, o):
        return self._binary(o, "_not_equal")

    def __gt__(self, o):
        return self._binary(o, "_greater")

    def __ge__(self, o):
        return self._binary(o, "_greater_equal")

    def __lt__(self, o):
        return self._binary(o, "_lesser")

    def __le__(self, o):
        return self._binary(o, "_lesser_equal")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    # in-place: functional rebind preserving view write-through
    def __iadd__(self, o):
        out = self._binary(o, "elemwise_add")
        self._set_data(out.data)
        return self

    def __isub__(self, o):
        out = self._binary(o, "elemwise_sub")
        self._set_data(out.data)
        return self

    def __imul__(self, o):
        out = self._binary(o, "elemwise_mul")
        self._set_data(out.data)
        return self

    def __itruediv__(self, o):
        out = self._binary(o, "elemwise_div")
        self._set_data(out.data)
        return self

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req: str = "write") -> None:
        """Allocate a gradient buffer and mark for recording
        (gluon-style; ``MXAutogradMarkVariables`` under the hood)."""
        from .. import autograd

        import jax.numpy as jnp

        self.grad = NDArray(jnp.zeros_like(self.data), ctx=self._ctx)
        self._grad_req = grad_req
        autograd.mark_variables([self], [self.grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self) -> "NDArray":
        out = NDArray(self.data, ctx=self._ctx)
        return out

    # convenience reductions mirroring mx.nd methods
    def sum(self, *args, **kwargs):
        from . import op_invoke

        return op_invoke("sum", [self], kwargs)

    def mean(self, *args, **kwargs):
        from . import op_invoke

        return op_invoke("mean", [self], kwargs)

    def max(self, *args, **kwargs):
        from . import op_invoke

        return op_invoke("max", [self], kwargs)

    def min(self, *args, **kwargs):
        from . import op_invoke

        return op_invoke("min", [self], kwargs)

    def argmax(self, **kwargs):
        from . import op_invoke

        return op_invoke("argmax", [self], kwargs)

    def as_nd_ndarray(self):
        return self


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """``mx.nd.array`` — create from any array-like."""
    import jax

    if isinstance(source, NDArray):
        source = source.asnumpy()
    if dtype is None:
        # reference semantics: numpy keeps its dtype (except float64→float32
        # the TPU-native default real type), python lists default to float32
        dtype = source.dtype if isinstance(source, np.ndarray) else np.float32
        if dtype == np.float64:
            dtype = np.float32
    arr = np.asarray(source, dtype=dtype_np(dtype))
    ctx = ctx or current_context()
    return NDArray(jax.device_put(arr, ctx.jax_device), ctx=ctx)


def empty(shape, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    # delegate to THE constant-fill path (host numpy + one device_put)
    from . import zeros as nd_zeros

    return nd_zeros(shape, ctx=ctx, dtype=dtype)


def waitall() -> None:
    engine().wait_for_all()
