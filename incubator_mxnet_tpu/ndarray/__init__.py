"""``mx.nd`` — imperative op namespace, auto-generated from the op registry.

Reference analog: ``python/mxnet/ndarray.py`` ops generated at import from the
C op registry via ``_init_ndarray_module``; each call is one
``MXImperativeInvoke`` (``src/c_api/c_api_ndarray.cc:423``).  Here the invoke
path is: unwrap jax arrays → OpContext (train flag + PRNG key) → op forward
(async jax dispatch) → wrap outputs → optional autograd tape record.
"""
from __future__ import annotations

import struct
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import autograd as _autograd
from .. import random as _random
from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from ..engine import engine
from ..ops.registry import OPS, OpContext, OpDef, get_op
from .ndarray import NDArray, array, empty, waitall

__all__ = ["NDArray", "array", "empty", "waitall", "op_invoke", "zeros",
           "ones", "full", "arange", "save", "load", "concatenate",
           "onehot_encode", "imports_done"]


def op_invoke(op, inputs: Sequence[NDArray], attrs: Optional[Dict] = None,
              out=None):
    """Invoke one operator imperatively (MXImperativeInvoke analog)."""
    opdef: OpDef = op if isinstance(op, OpDef) else get_op(op)
    attrs = dict(attrs or {})
    ctx = inputs[0].context if inputs else attrs.pop("ctx", None) or \
        attrs.pop("context", None) or current_context()
    if isinstance(ctx, str):
        parts = ctx.split("(")
        ctx = Context(parts[0], int(parts[1][:-1]) if len(parts) > 1 else 0)

    in_vals = [a.data for a in inputs]
    opctx = OpContext(
        is_train=_autograd.is_training(),
        rng=_random.next_key() if opdef.needs_rng else None)

    def _run():
        return opdef.apply(in_vals, attrs, opctx)

    outs, new_aux = engine().push(_run, name=opdef.name)

    arg_names = opdef.get_arg_names(attrs)
    n_args = len(arg_names) if arg_names is not None else len(inputs)
    if opdef.has_aux:
        # NB: can't use builtin min() here — generated ops shadow it in this
        # module's namespace
        cap = len(inputs) - len(opdef.aux_names)
        if cap < n_args:
            n_args = cap
        # write aux updates back in place (reference mutates aux NDArrays)
        for aux_nd, val in zip(inputs[n_args:], new_aux):
            aux_nd._set_data(val)

    if opdef.mutate_inputs:
        for i, inp_idx in enumerate(opdef.mutate_inputs):
            if i < len(outs) and inp_idx < len(inputs):
                if inp_idx == opdef.mutate_inputs[0] and out is not None:
                    continue
                inputs[inp_idx]._set_data(outs[i])

    out_nds = [NDArray(o, ctx=ctx if inputs else ctx) for o in outs]

    if out is not None:
        targets = out if isinstance(out, (list, tuple)) else [out]
        for t, o in zip(targets, out_nds):
            t._set_data(o.data)
        out_nds = list(targets) + out_nds[len(targets):]

    if _autograd.is_recording() and inputs and not opdef.mutate_inputs:
        _autograd.record_op(opdef, attrs, opctx, inputs, in_vals, out_nds,
                            n_args)

    if len(out_nds) == 1:
        return out_nds[0]
    return out_nds


def _make_op_func(opdef: OpDef, name: str):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        # split NDArray inputs from attrs
        inputs: List[NDArray] = [a for a in args if isinstance(a, NDArray)]
        attrs = {k: v for k, v in kwargs.items()
                 if not isinstance(v, NDArray)}
        arg_names = opdef.get_arg_names(attrs)
        if arg_names is not None:
            expected = list(arg_names) + list(opdef.aux_names)
            by_name = {k: v for k, v in kwargs.items()
                       if isinstance(v, NDArray)}
            merged: List[NDArray] = list(inputs)
            for nm in expected[len(merged):]:
                if nm in by_name:
                    merged.append(by_name[nm])
            inputs = merged
        else:
            inputs += [v for k, v in kwargs.items() if isinstance(v, NDArray)]
        # numpy/scalar positional data for creation-style usage
        return op_invoke(opdef, inputs, attrs, out=out)

    fn.__name__ = name
    fn.__doc__ = opdef.doc
    fn.__module__ = __name__
    return fn


def _install_ops():
    mod = sys.modules[__name__]
    seen = {}
    for name in OPS.keys():
        opdef = OPS.get(name)
        public = opdef.name
        # install under every registered alias, preserving case via opdef
        for alias in [opdef.name] + opdef.aliases:
            if not hasattr(mod, alias):
                setattr(mod, alias, _make_op_func(opdef, alias))
        if name != opdef.name.lower() and not hasattr(mod, name):
            setattr(mod, name, _make_op_func(opdef, name))
        seen[public] = opdef


_install_ops()
imports_done = True


# ---------------------------------------------------------------------------
# creation helpers with ctx (python/mxnet/ndarray.py zeros/ones/arange...)
# ---------------------------------------------------------------------------


def _ctx_put(arr, ctx: Optional[Context]):
    import jax

    ctx = ctx or current_context()
    return NDArray(jax.device_put(arr, ctx.jax_device), ctx=ctx)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    # host numpy + ONE device_put, never jnp.zeros: the device route
    # compiles an XLA program per unique shape over the tunnel
    # (seconds each on a bad-weather day) and, when ctx differs from
    # the default device, round-trips the buffer through the ~5 MB/s
    # D2H path (PERF.md §1) — constant-fill creation belongs on host
    if isinstance(shape, int):
        shape = (shape,)
    return _ctx_put(np.zeros(shape, dtype_np(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return _ctx_put(np.ones(shape, dtype_np(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return _ctx_put(np.full(shape, val, dtype_np(dtype)), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    # host numpy like zeros(): the jnp route compiles an iota program
    # per unique length on the default device and migrates cross-ctx
    if stop is None:
        start, stop = 0, start
    out = np.arange(start, stop, step, dtype=dtype_np(dtype))
    if repeat > 1:
        out = np.repeat(out, repeat)
    return _ctx_put(out, ctx)


def concatenate(arrays: Sequence[NDArray], axis: int = 0,
                always_copy: bool = True) -> NDArray:
    import jax.numpy as jnp

    return NDArray(jnp.concatenate([a.data for a in arrays], axis=axis),
                   ctx=arrays[0]._ctx)


def onehot_encode(indices: NDArray, out: NDArray) -> NDArray:
    import jax

    depth = out.shape[1]
    oh = jax.nn.one_hot(indices.data.astype("int32"), depth,
                        dtype=out.data.dtype)
    out._set_data(oh)
    return out


# ---------------------------------------------------------------------------
# Serialization — the GENUINE reference container format, byte for byte
# (``src/ndarray/ndarray.cc:668-744``): u64 kMXAPINDArrayListMagic +
# u64 reserved, dmlc vector<NDArray> (u64 count; per array u32
# NDARRAY_V1_MAGIC, u32 ndim + i64 dims, i32 dev_type + i32 dev_id,
# i32 mshadow type_flag, raw data), dmlc vector<string> names.  Files
# written by MXNet v0.11's ``mx.nd.save`` load here and vice versa;
# ``load`` also reads the legacy pre-0.9 TShape framing (magic = ndim,
# u32 dims — ``LegacyTShapeLoad``, ndarray.cc:693) and this repo's
# round-3 container.
# ---------------------------------------------------------------------------

_NDARRAY_MAGIC = 0x112           # kMXAPINDArrayListMagic
_NDARRAY_V1_MAGIC = 0xF993FAC8   # per-array shape magic
_FMT_VERSION = 1                 # round-3 own-format version sentinel

# mshadow::TypeFlag (mshadow/base.h) — bf16 postdates v0.11 and has no
# flag; masters are f32, so bf16 arrays upcast on save
_TYPE_FLAGS = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
               "int32": 4, "int8": 5, "int64": 6}
_FLAG_TYPES = {v: k for k, v in _TYPE_FLAGS.items()}


def save(fname: str, data) -> None:
    """Save dict/list of NDArrays (``MXNDArraySave``) in the genuine
    reference binary format."""
    if isinstance(data, NDArray):
        names, arrays = [], [data]
    elif isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    else:
        names, arrays = [], list(data)
    from ..filesystem import open_uri

    # open_uri gives remote URIs the clear "read-only" diagnostic
    # instead of a baffling FileNotFoundError on 's3:/...'
    with open_uri(fname, "wb") as f:
        f.write(struct.pack("<QQ", _NDARRAY_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for arr in arrays:
            a = np.ascontiguousarray(arr.asnumpy())
            if a.ndim == 0:
                # ndim==0 means "empty NDArray" to the reference loader
                # (ndarray.cc early-returns without consuming Context/
                # type/data) — a scalar written as ndim=0 + payload
                # would desync every later array; persist as (1,)
                a = a.reshape(1)
            if a.dtype.name == "bfloat16" or a.dtype.name not in _TYPE_FLAGS:
                a = a.astype(np.float32)
            f.write(struct.pack("<I", _NDARRAY_V1_MAGIC))
            f.write(struct.pack("<I", a.ndim))
            f.write(struct.pack("<%dq" % a.ndim, *a.shape)
                    if a.ndim else b"")
            f.write(struct.pack("<ii", 1, 0))  # Context: kCPU, dev 0
            f.write(struct.pack("<i", _TYPE_FLAGS[a.dtype.name]))
            f.write(a.tobytes())
        f.write(struct.pack("<Q", len(names)))
        for name in names:
            nb = name.encode("utf-8")
            f.write(struct.pack("<Q", len(nb)))
            f.write(nb)


def _load_one_reference(f):
    (magic,) = struct.unpack("<I", f.read(4))
    if magic == _NDARRAY_V1_MAGIC:
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim)) \
            if ndim else ()
    else:
        # pre-0.9 legacy TShape: the magic word IS ndim, u32 dims
        ndim = magic
        shape = struct.unpack("<%dI" % ndim, f.read(4 * ndim)) \
            if ndim else ()
    if ndim == 0:
        return array(np.zeros((), np.float32))
    f.read(8)  # Context (dev_type, dev_id) — always loaded to host
    (type_flag,) = struct.unpack("<i", f.read(4))
    if type_flag not in _FLAG_TYPES:
        raise MXNetError("unknown mshadow type flag %d" % type_flag)
    dt = np.dtype(_FLAG_TYPES[type_flag])
    n = int(np.prod(shape))
    a = np.frombuffer(f.read(n * dt.itemsize), dtype=dt).reshape(shape)
    return array(a, dtype=dt)


def load(fname: str):
    """Load dict/list of NDArrays (``MXNDArrayLoad``) — genuine
    reference files (incl. pre-0.9 shape framing) and this repo's
    round-3 container.  Accepts stream URIs (http/s3/hdfs) like the
    reference's dmlc Stream path (``ndarray.cc`` Load over
    ``Stream::Create``) — checkpoints pull straight from object
    stores."""
    from ..filesystem import open_uri

    with open_uri(fname, "rb") as f:
        magic, word2 = struct.unpack("<QQ", f.read(16))
        if magic != _NDARRAY_MAGIC:
            raise MXNetError("invalid NDArray file %s" % fname)
        if word2 == _FMT_VERSION:
            # round-3 own container (version sentinel; the reference
            # always writes reserved = 0 here)
            return _load_own_v1(f)
        (count,) = struct.unpack("<Q", f.read(8))
        arrays = [_load_one_reference(f) for _ in range(count)]
        (nnames,) = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(nnames):
            (ln,) = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    if names:
        return dict(zip(names, arrays))
    return arrays


def _load_own_v1(f):
    (count,) = struct.unpack("<Q", f.read(8))
    names, arrays = [], []
    for _ in range(count):
        (nlen,) = struct.unpack("<I", f.read(4))
        name = f.read(nlen).decode("utf-8")
        (dlen,) = struct.unpack("<I", f.read(4))
        dt = np.dtype(f.read(dlen).decode("utf-8"))
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim)) if ndim \
            else ()
        (blen,) = struct.unpack("<Q", f.read(8))
        a = np.frombuffer(f.read(blen), dtype=dt).reshape(shape)
        names.append(name)
        arrays.append(array(a, dtype=dt))
    if any(names):
        return dict(zip(names, arrays))
    return arrays
