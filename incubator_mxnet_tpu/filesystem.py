"""Stream-URI filesystem layer: local, http(s), S3, HDFS record streams.

Reference analog: the dmlc-core Stream URI dispatch the reference's IO
stack is built on — ``dmlc::Stream::Create("s3://...")`` lets RecordIO
iterators read straight from S3/HDFS when built with ``USE_S3=1`` /
``USE_HDFS=1`` (``make/config.mk:133-141``).  TPU-native redesign: a
pure-python scheme dispatch returning file-like objects; remote
schemes are CHUNKED RANGE READERS (real streaming with random access
— ``seek``/``read`` over HTTP Range / S3 ranged GET — not
download-the-world), so ``IndexedRecordIO``'s seeks and the
sequential scanner both work unchanged over remote packs.

Backends:
- (none) / ``file://`` — local ``open`` (read/write);
- ``http://`` / ``https://`` — stdlib ``urllib`` Range requests;
- ``s3://bucket/key`` — ``boto3`` ranged ``get_object`` (gated: a
  clear ``MXNetError`` when boto3 is absent, matching the reference's
  compile-time ``USE_S3`` gate at runtime);
- ``hdfs://`` — ``pyarrow.fs.HadoopFileSystem`` (gated likewise).

Remote streams are read-only; remote WRITE raises (the reference's S3
write path needed the same credentials machinery — out of scope for a
zero-egress build).
"""
from __future__ import annotations

import io
from typing import Optional, Tuple

from .base import MXNetError, get_env

__all__ = ["parse_uri", "open_uri", "is_remote", "is_not_found",
           "RangeStream", "HTTPRangeStream", "S3RangeStream"]

# chunk granularity for remote range reads: big enough to amortize
# request latency over JPEG-sized records, small enough that an
# indexed seek does not refetch megabytes
_CHUNK = 1 << 20


def parse_uri(uri: str) -> Tuple[str, str]:
    """``uri`` → (scheme, rest); local paths have scheme ''."""
    if "://" not in uri:
        return "", uri
    scheme, rest = uri.split("://", 1)
    return scheme.lower(), rest


def is_remote(uri: str) -> bool:
    return parse_uri(uri)[0] in ("http", "https", "s3", "hdfs")


def is_not_found(exc: BaseException) -> bool:
    """True when ``exc`` means "object does not exist" (HTTP 404 /
    S3 NoSuchKey / local ENOENT) — callers distinguishing a MISSING
    sidecar from auth/network failures must not swallow the latter."""
    if isinstance(exc, FileNotFoundError):
        return True
    if getattr(exc, "code", None) == 404:        # urllib HTTPError
        return True
    resp = getattr(exc, "response", None)        # botocore ClientError
    if isinstance(resp, dict):
        code = str(resp.get("Error", {}).get("Code", ""))
        if code in ("404", "NoSuchKey", "NotFound"):
            return True
        if str(resp.get("ResponseMetadata", {})
               .get("HTTPStatusCode", "")) == "404":
            return True
    return False


def _timeout() -> float:
    # one wedged connection must not hang a prefetch worker (and with
    # it every reader queued on the record lock) forever
    return float(get_env("REMOTE_TIMEOUT", 60, int))


class RangeStream(io.RawIOBase):
    """File-like over an abstract ranged fetch: ``_fetch(start, stop)``
    returns bytes, ``_length()`` the object size.  Reads go through an
    aligned chunk cache so sequential scans issue one request per
    ``_CHUNK`` and indexed seeks only fetch the chunks they touch."""

    def __init__(self, cache_chunks: int = 8):
        super().__init__()
        self._pos = 0
        self._size: Optional[int] = None
        self._cache = {}          # chunk index -> bytes (LRU by dict order)
        self._max_chunks = max(int(cache_chunks), 1)

    # -- abstract -----------------------------------------------------
    def _fetch(self, start: int, stop: int) -> bytes:
        raise NotImplementedError

    def _length(self) -> int:
        raise NotImplementedError

    # -- io surface ---------------------------------------------------
    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    @property
    def size(self) -> int:
        if self._size is None:
            self._size = int(self._length())
        return self._size

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        elif whence == 2:
            self._pos = self.size + offset
        else:
            raise ValueError("bad whence %r" % whence)
        return self._pos

    def tell(self) -> int:
        return self._pos

    def _chunk(self, ci: int) -> bytes:
        buf = self._cache.pop(ci, None)
        if buf is None:
            start = ci * _CHUNK
            stop = min(start + _CHUNK, self.size)
            buf = self._fetch(start, stop)
        self._cache[ci] = buf     # reinsert = most-recently-used
        while len(self._cache) > self._max_chunks:
            self._cache.pop(next(iter(self._cache)))
        return buf

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = max(self.size - self._pos, 0)
        n = min(n, max(self.size - self._pos, 0))
        out = []
        pos = self._pos
        remaining = n
        while remaining > 0:
            ci, off = divmod(pos, _CHUNK)
            buf = self._chunk(ci)
            piece = buf[off:off + remaining]
            if not piece:
                break
            out.append(piece)
            pos += len(piece)
            remaining -= len(piece)
        self._pos = pos
        return b"".join(out)


class HTTPRangeStream(RangeStream):
    """http(s) object via stdlib urllib Range requests."""

    def __init__(self, url: str, cache_chunks: int = 8):
        super().__init__(cache_chunks)
        self.url = url

    def _length(self) -> int:
        import urllib.request

        req = urllib.request.Request(self.url, method="HEAD")
        with urllib.request.urlopen(req, timeout=_timeout()) as r:
            cl = r.headers.get("Content-Length")
            if cl is None:
                raise MXNetError("remote %s sent no Content-Length"
                                 % self.url)
            return int(cl)

    def _fetch(self, start: int, stop: int) -> bytes:
        import urllib.request

        req = urllib.request.Request(
            self.url, headers={"Range": "bytes=%d-%d"
                               % (start, stop - 1)})
        with urllib.request.urlopen(req, timeout=_timeout()) as r:
            body = r.read()
        # a server that ignores Range returns 200 + the full body:
        # slicing chunk-relative offsets into it would silently read
        # the wrong bytes — fail loudly instead
        if len(body) != stop - start:
            raise MXNetError(
                "server for %s ignored the Range request (wanted "
                "%d bytes [%d, %d), got %d) — remote record streams "
                "need Range support"
                % (self.url, stop - start, start, stop, len(body)))
        return body


class S3RangeStream(RangeStream):
    """s3://bucket/key via boto3 ranged GETs (runtime analog of the
    reference's USE_S3 build gate)."""

    def __init__(self, bucket: str, key: str, cache_chunks: int = 8):
        super().__init__(cache_chunks)
        try:
            import boto3
        except ImportError:
            raise MXNetError(
                "s3:// record streams need boto3 (the reference gates "
                "the same capability behind USE_S3=1); pip install "
                "boto3 or pre-stage the pack locally")
        self.bucket, self.key = bucket, key
        self._client = boto3.client("s3")

    def _length(self) -> int:
        head = self._client.head_object(Bucket=self.bucket,
                                        Key=self.key)
        return int(head["ContentLength"])

    def _fetch(self, start: int, stop: int) -> bytes:
        obj = self._client.get_object(
            Bucket=self.bucket, Key=self.key,
            Range="bytes=%d-%d" % (start, stop - 1))
        return obj["Body"].read()


def _open_hdfs(rest: str, mode: str):
    try:
        from pyarrow import fs as pafs
    except ImportError:
        raise MXNetError(
            "hdfs:// record streams need pyarrow (the reference gates "
            "the same capability behind USE_HDFS=1)")
    host, _, path = rest.partition("/")
    h, _, p = host.partition(":")
    hdfs = pafs.HadoopFileSystem(h or "default",
                                 int(p) if p else 8020)
    return hdfs.open_input_file("/" + path)


def open_uri(uri: str, mode: str = "rb"):
    """dmlc ``Stream::Create`` analog: open ``uri`` per its scheme.

    Local paths (and ``file://``) honor ``mode``; remote schemes are
    read-only chunked range streams.  ``TP_REMOTE_CACHE_CHUNKS``
    tunes the per-stream chunk cache (default 8 × 1 MB)."""
    scheme, rest = parse_uri(uri)
    if scheme in ("", "file"):
        return open(rest if scheme else uri, mode)
    if "r" not in mode:
        raise MXNetError(
            "remote record streams are read-only (%s)" % uri)
    chunks = get_env("REMOTE_CACHE_CHUNKS", 8, int)
    if scheme in ("http", "https"):
        return HTTPRangeStream(uri, chunks)
    if scheme == "s3":
        bucket, _, key = rest.partition("/")
        return S3RangeStream(bucket, key, chunks)
    if scheme == "hdfs":
        return _open_hdfs(rest, mode)
    raise MXNetError("unsupported stream scheme %r (%s)" % (scheme, uri))
