"""Base utilities for the TPU-native MXNet rebuild.

Provides the capabilities MXNet sourced from the (absent) ``dmlc-core``
submodule: env-var config (``dmlc::GetEnv``), logging/``CHECK_*`` macros,
registries, and dtype plumbing.  See reference ``include/mxnet/base.h`` and
SURVEY.md layer 0.

This file is an original TPU-first design, not a translation: there is no
ctypes/C-ABI layer because the compute substrate is JAX/XLA, which is already
in-process.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Callable, Dict, Optional

import numpy as np

__all__ = [
    "MXNetError", "check", "get_env", "string_types", "numeric_types",
    "Registry", "mx_real_t", "dtype_np", "dtype_name", "_Null", "_NullType",
]

# ---------------------------------------------------------------------------
# Errors / logging (dmlc-core LOG/CHECK equivalents)
# ---------------------------------------------------------------------------


class MXNetError(RuntimeError):
    """Framework error type (mirrors ``dmlc::Error`` / MXNetError in the
    reference C API, ``src/c_api/c_api_error.cc``)."""


def check(cond: bool, msg: str = "check failed") -> None:
    """``CHECK(cond) << msg`` equivalent."""
    if not cond:
        raise MXNetError(msg)


logger = logging.getLogger("mxnet_tpu")


# ---------------------------------------------------------------------------
# Env-var config registry (``dmlc::GetEnv``; docs/how_to/env_var.md)
# ---------------------------------------------------------------------------

_ENV_PREFIXES = ("MXNET_", "TP_")


def get_env(name: str, default: Any = None, typ: type = str) -> Any:
    """Read a config env var.  Accepts both the reference's ``MXNET_*`` names
    (so reference-era scripts keep working) and native ``TP_*`` names.

    ``get_env("ENGINE_TYPE", "ThreadedEnginePerDevice")`` checks
    ``TP_ENGINE_TYPE`` then ``MXNET_ENGINE_TYPE``.
    """
    for prefix in ("TP_", "MXNET_"):
        v = os.environ.get(prefix + name)
        if v is not None:
            if typ is bool:
                return v not in ("0", "false", "False", "")
            return typ(v)
    return default


# ---------------------------------------------------------------------------
# Generic registry (mirrors dmlc registry used by ops/optimizers/metrics/inits)
# ---------------------------------------------------------------------------


class Registry:
    """Name → object registry with decorator support.

    Equivalent in capability to the dmlc registry pattern used throughout the
    reference (e.g. ``python/mxnet/registry.py``, optimizer registry at
    ``python/mxnet/optimizer.py:30``).
    """

    def __init__(self, kind: str, case_sensitive: bool = False):
        self.kind = kind
        self.case_sensitive = case_sensitive
        self._store: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _key(self, name: str) -> str:
        return name if self.case_sensitive else name.lower()

    def register(self, obj: Any = None, name: Optional[str] = None):
        def _do(o):
            key = self._key(name or getattr(o, "__name__", None) or str(o))
            with self._lock:
                if key in self._store and self._store[key] is not o:
                    logger.warning("%s '%s' overridden", self.kind, key)
                self._store[key] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def alias(self, name: str, target: str) -> None:
        self._store[self._key(name)] = self._store[self._key(target)]

    def get(self, name: str) -> Any:
        key = self._key(name)
        if key not in self._store:
            raise MXNetError(
                "unknown %s '%s'; registered: %s"
                % (self.kind, name, sorted(self._store)))
        return self._store[key]

    def find(self, name: str) -> Optional[Any]:
        return self._store.get(self._key(name))

    def __contains__(self, name: str) -> bool:
        return self._key(name) in self._store

    def keys(self):
        return sorted(self._store)

    def create(self, name: str, *args, **kwargs):
        return self.get(name)(*args, **kwargs)


# ---------------------------------------------------------------------------
# dtypes (mirrors mshadow dtype switch; include/mxnet/base.h:128-134)
# ---------------------------------------------------------------------------

mx_real_t = np.float32

_DTYPE_ALIASES: Dict[str, np.dtype] = {
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
    "float16": np.dtype(np.float16),
    "bfloat16": None,  # filled lazily to avoid importing jax at module import
    "uint8": np.dtype(np.uint8),
    "int8": np.dtype(np.int8),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "bool": np.dtype(np.bool_),
}


def dtype_np(dtype) -> Any:
    """Normalize a user-facing dtype (str | np.dtype | type) to a numpy/ml
    dtype object usable by jax."""
    if dtype is None:
        return np.dtype(mx_real_t)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import ml_dtypes  # shipped with jax

            return np.dtype(ml_dtypes.bfloat16)
        if dtype in _DTYPE_ALIASES and _DTYPE_ALIASES[dtype] is not None:
            return _DTYPE_ALIASES[dtype]
        return np.dtype(dtype)
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    d = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    return d.name


string_types = (str,)
numeric_types = (float, int, np.generic)


class _NullType:
    """Placeholder for missing op attrs (mirrors mxnet.base._Null)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "_Null"

    def __bool__(self):
        return False


_Null = _NullType()
