"""Logging utilities (reference ``python/mxnet/log.py``): a colored
single-letter-level formatter and ``get_logger``."""
from __future__ import annotations

import logging
import sys

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

__all__ = ["get_logger", "CRITICAL", "ERROR", "WARNING", "INFO", "DEBUG",
           "NOTSET"]

_LABELS = {logging.CRITICAL: "C", logging.ERROR: "E",
           logging.WARNING: "W", logging.INFO: "I", logging.DEBUG: "D"}


class _Formatter(logging.Formatter):
    """``L MMDD HH:MM:SS pid file:line] msg`` with ANSI level colors on
    ttys (the reference glog-style line)."""

    def __init__(self, colored: bool):
        super().__init__(datefmt="%m%d %H:%M:%S")
        self._colored = colored

    def _color(self, level):
        if level >= logging.WARNING:
            return "\x1b[31m"
        if level >= logging.INFO:
            return "\x1b[32m"
        return "\x1b[34m"

    def format(self, record):
        label = _LABELS.get(record.levelno, "U")
        head = "%s%s %s %s:%d]" % (
            label, "", self.formatTime(record, self.datefmt),
            record.filename, record.lineno)
        if self._colored:
            head = self._color(record.levelno) + head + "\x1b[0m"
        return "%s %s" % (head, record.getMessage())


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """Logger with the framework formatter attached once
    (reference ``log.py:getLogger``)."""
    logger = logging.getLogger(name)
    if getattr(logger, "_tp_log_init", False):
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        colored = False
    else:
        handler = logging.StreamHandler(sys.stderr)
        colored = getattr(sys.stderr, "isatty", lambda: False)()
    handler.setFormatter(_Formatter(colored))
    logger.addHandler(handler)
    logger.setLevel(level)
    # this logger has its own formatter; propagating to a configured
    # root handler would print every record twice
    logger.propagate = False
    logger._tp_log_init = True
    return logger


getLogger = get_logger  # reference spelling
