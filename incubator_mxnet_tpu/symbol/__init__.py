"""``mx.sym`` — symbolic graph frontend.

Reference analog: ``nnvm::Symbol`` composition + ``python/mxnet/symbol.py``
(compose, infer shape/type, save/load JSON, simple_bind).  TPU-native
redesign: a Symbol is a lightweight DAG over the same op registry the
imperative frontend uses; *binding* lowers the DAG to one jax function that
``jax.jit`` compiles — the jit boundary is the analog of the reference's
bulk-exec segment (``graph_executor.cc:1130``), and XLA replaces the NNVM
passes (InferShape/Type eagerly here for API parity and error messages;
PlanMemory/fusion inside XLA).
"""
from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import attribute, name as _name_mod
from ..base import MXNetError, dtype_np, dtype_name
from ..ops.registry import OPS, OpDef, get_op

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones"]


class _Node:
    """One graph node (op application or variable)."""

    __slots__ = ("op", "name", "attrs", "inputs", "_num_outputs")

    def __init__(self, op: Optional[OpDef], name: str,
                 attrs: Dict[str, Any], inputs: List[Tuple["_Node", int]]):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self._num_outputs = 1 if op is None else op.get_num_outputs(attrs)

    @property
    def is_variable(self) -> bool:
        return self.op is None

    def output_names(self) -> List[str]:
        if self.op is None:
            return [self.name]
        n = self._num_outputs
        if n == 1:
            return ["%s_output" % self.name]
        return ["%s_output%d" % (self.name, i) for i in range(n)]

    def aux_input_count(self) -> int:
        return len(self.op.aux_names) if self.op is not None else 0


class Symbol:
    """A set of output entries over the node DAG."""

    def __init__(self, outputs: List[Tuple[_Node, int]]):
        self._outputs = outputs

    # ------------------------------------------------------------- structure
    @property
    def name(self) -> str:
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        names = ", ".join(n.name for n, _ in self._outputs)
        return "<Symbol %s>" % names

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            for i, nm in enumerate(self.list_outputs()):
                if nm == index:
                    return Symbol([self._outputs[i]])
            raise MXNetError("no output named %s" % index)
        return Symbol([self._outputs[index]])

    def topo_nodes(self) -> List[_Node]:
        """Topological order of all nodes reachable from outputs."""
        order, seen = [], set()

        def visit(node: _Node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for n, _ in self._outputs:
            visit(n)
        return order

    def list_arguments(self) -> List[str]:
        """Leaf variable names in topo order, excluding aux states
        (``nnvm::Symbol::ListInputNames(kReadOnlyArgs)``) — single O(N)
        pass."""
        nodes = self.topo_nodes()
        aux = self._aux_var_names(nodes)
        args = []
        for node in nodes:
            if node.is_variable and node.name not in aux \
                    and node.name not in args:
                args.append(node.name)
        return args

    def list_auxiliary_states(self) -> List[str]:
        return list(self._aux_var_names(self.topo_nodes()))

    @staticmethod
    def _aux_var_names(nodes) -> "dict":
        """Ordered set of variable names feeding aux-input slots."""
        aux = {}
        for node in nodes:
            if node.op is not None and node.op.has_aux:
                n_args = len(node.op.get_arg_names(node.attrs))
                for pos, (inp, _) in enumerate(node.inputs):
                    if pos >= n_args and inp.is_variable:
                        aux.setdefault(inp.name, True)
        return aux

    def list_outputs(self) -> List[str]:
        out = []
        for node, idx in self._outputs:
            out.append(node.output_names()[idx])
        return out

    def get_internals(self) -> "Symbol":
        outs = []
        for node in self.topo_nodes():
            for i in range(node._num_outputs):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self) -> Optional["Symbol"]:
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # ------------------------------------------------------------------ attr
    def attr(self, key: str) -> Optional[str]:
        node = self._outputs[0][0]
        v = node.attrs.get(key)
        return str(v) if v is not None else None

    def list_attr(self) -> Dict[str, str]:
        node = self._outputs[0][0]
        return {k: str(v) for k, v in node.attrs.items()}

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for node in self.topo_nodes():
            if node.attrs:
                out[node.name] = {k: str(v) for k, v in node.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        self._outputs[0][0].attrs.update(kwargs)

    # ------------------------------------------------------------- inference
    def infer_shape(self, *args, **kwargs):
        arg_s, out_s, aux_s = self.infer_shape_partial(*args, **kwargs)
        if arg_s is not None and any(s is None for s in arg_s):
            missing = [n for n, s in zip(self.list_arguments(), arg_s)
                       if s is None]
            raise MXNetError("infer_shape incomplete; unknown shapes for "
                             "args %s" % missing)
        return arg_s, out_s, aux_s

    def infer_shape_partial(self, *args, **kwargs):
        """Forward shape propagation with per-op back-inference of parameter
        shapes — the capability the reference got from the fixed-point
        InferShape pass (``graph_executor.cc:826``)."""
        known: Dict[str, Tuple[int, ...]] = {}
        arg_names = self.list_arguments()
        if args:
            for nm, s in zip(arg_names, args):
                if s is not None:
                    known[nm] = tuple(s)
        for k, v in kwargs.items():
            known[k] = tuple(v)

        node_out_shapes: Dict[Tuple[int, int], Any] = {}
        var_shapes: Dict[str, Any] = {}

        for node in self.topo_nodes():
            if node.is_variable:
                s = known.get(node.name)
                if s is None:
                    sa = node.attrs.get("__shape__")
                    if sa is not None:
                        from ..ops.registry import parse_tuple

                        s = parse_tuple(sa)  # handles str round-trip via JSON
                # reference convention: a 0 dim means "unknown" — treat the
                # whole shape as uninferred so op rules back-fill it
                if s is not None and any(d == 0 for d in s):
                    s = None
                var_shapes.setdefault(node.name, s)
                node_out_shapes[(id(node), 0)] = var_shapes[node.name]
                continue
            in_shapes = []
            for inp, idx in node.inputs:
                if inp.is_variable:
                    in_shapes.append(var_shapes.get(inp.name))
                else:
                    in_shapes.append(node_out_shapes.get((id(inp), idx)))
            out_shapes = self._infer_node(node, in_shapes)
            for i, s in enumerate(out_shapes):
                node_out_shapes[(id(node), i)] = s
            # back-fill inferred input shapes into variables
            for (inp, idx), s in zip(node.inputs, self._last_in_shapes):
                if inp.is_variable and s is not None \
                        and var_shapes.get(inp.name) is None:
                    var_shapes[inp.name] = tuple(s)
                    node_out_shapes[(id(inp), 0)] = tuple(s)

        arg_shapes = [var_shapes.get(n) for n in arg_names]
        out_shapes = [node_out_shapes.get((id(n), i))
                      for n, i in self._outputs]
        aux_shapes = [var_shapes.get(n)
                      for n in self.list_auxiliary_states()]
        return arg_shapes, out_shapes, aux_shapes

    def _infer_node(self, node: _Node, in_shapes):
        """Infer node output shapes; uses the op rule if present, else
        jax.eval_shape over the forward."""
        op = node.op
        if op.infer_shape is not None:
            ins, outs, aux = op.infer_shape(
                list(in_shapes), node.attrs)
            self._last_in_shapes = list(ins) + list(aux)
            return [tuple(s) if s is not None else None for s in outs]
        self._last_in_shapes = in_shapes
        if any(s is None for s in in_shapes):
            n = op.get_num_outputs(node.attrs)
            return [None] * n
        import jax

        from ..ops.registry import OpContext

        def f(*arrs):
            outs, _aux = op.apply(list(arrs), node.attrs,
                                  OpContext(is_train=False, rng=None))
            return tuple(outs)

        specs = [jax.ShapeDtypeStruct(tuple(s), np.float32)
                 for s in in_shapes]
        try:
            out = jax.eval_shape(f, *specs)
        except Exception as e:
            raise MXNetError("shape inference failed at node %s (%s): %s"
                             % (node.name, op.name, e))
        return [tuple(o.shape) for o in out]

    def infer_type(self, *args, **kwargs):
        """Type inference: default real type everywhere except explicitly
        typed variables (simplified vs the reference but same API)."""
        arg_names = self.list_arguments()
        known = {}
        if args:
            for nm, t in zip(arg_names, args):
                if t is not None:
                    known[nm] = t
        known.update(kwargs)
        arg_types = [known.get(n, np.float32) for n in arg_names]
        out_types = [np.float32] * len(self._outputs)
        aux_types = [np.float32] * len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # ------------------------------------------------------------ arithmetic
    def _compose_binary(self, other, opname, scalar_op, rscalar_op=None,
                        rop=False):
        if isinstance(other, Symbol):
            return _create(opname, [self, other], {})
        op = rscalar_op if (rop and rscalar_op) else scalar_op
        return _create(op, [self], {"scalar": other})

    def __add__(self, o):
        return self._compose_binary(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._compose_binary(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._compose_binary(o, "elemwise_sub", "_minus_scalar",
                                    "_rminus_scalar", rop=True)

    def __mul__(self, o):
        return self._compose_binary(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._compose_binary(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._compose_binary(o, "elemwise_div", "_div_scalar",
                                    "_rdiv_scalar", rop=True)

    def __pow__(self, o):
        return self._compose_binary(o, "_power", "_power_scalar")

    def __neg__(self):
        return _create("negative", [self], {})

    def __gt__(self, o):
        return self._compose_binary(o, "_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._compose_binary(o, "_greater_equal",
                                    "_greater_equal_scalar")

    def __lt__(self, o):
        return self._compose_binary(o, "_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._compose_binary(o, "_lesser_equal",
                                    "_lesser_equal_scalar")

    # ---------------------------------------------------------------- binder
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, **kwargs):
        from ..executor import Executor

        return Executor._simple_bind(self, ctx, grad_req, type_dict,
                                     group2ctx, shared_exec, kwargs)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor

        return Executor._bind(self, ctx, args, args_grad, grad_req,
                              aux_states, group2ctx, shared_exec)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, args=kwargs)
        return ex.forward()

    # ------------------------------------------------------------------ save
    def tojson(self) -> str:
        """Graph JSON (same structural idea as the reference symbol JSON:
        nodes list + arg_nodes + heads)."""
        nodes = self.topo_nodes()
        nid = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            out_nodes.append({
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "attrs": {k: str(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(i)], idx, 0] for i, idx in n.inputs],
            })
        return json.dumps({
            "nodes": out_nodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_variable],
            "heads": [[nid[id(n)], i, 0] for n, i in self._outputs],
            "attrs": {"tp_version": [1, 0]},
        }, indent=2)

    def save(self, fname: str) -> None:
        with open(fname, "w") as f:
            f.write(self.tojson())


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------


def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs) -> Symbol:
    """``mx.sym.Variable`` (``python/mxnet/symbol.py`` Variable)."""
    if not isinstance(name, str):
        raise TypeError("Variable name must be str")
    attrs = attribute.current().get(attr)
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if dtype is not None:
        attrs["__dtype__"] = dtype_name(dtype_np(dtype))
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else \
            init.dumps() if hasattr(init, "dumps") else str(init)
    attrs.update({k: str(v) for k, v in kwargs.items()})
    node = _Node(None, name, attrs, [])
    return Symbol([(node, 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _create(op_name: str, input_syms: List[Symbol], attrs: Dict[str, Any],
            name: Optional[str] = None,
            kw_inputs: Optional[Dict[str, Symbol]] = None) -> Symbol:
    """Compose an op node; auto-create missing parameter variables the way
    the reference auto-lists them (conv weight/bias appear in
    list_arguments without the user declaring them)."""
    op = get_op(op_name)
    attrs = dict(attrs)
    scope_attrs = attribute.current().get(None)
    name = _name_mod.current().get(name, op.name)

    arg_names = op.get_arg_names(attrs)
    inputs: List[Tuple[_Node, int]] = []
    if arg_names is None:
        for s in input_syms:
            if len(s._outputs) != 1:
                raise MXNetError("cannot compose multi-output symbol as "
                                 "a single input")
            inputs.append(s._outputs[0])
        attrs.setdefault("num_args", len(input_syms))
    else:
        expected = list(arg_names) + list(op.aux_names)
        pos = list(input_syms)
        kw_inputs = kw_inputs or {}
        for i, arg in enumerate(expected):
            if i < len(pos):
                s = pos[i]
            elif arg in kw_inputs:
                s = kw_inputs[arg]
            else:
                # auto-create variable "{name}_{arg}"
                s = Variable("%s_%s" % (name, arg))
            if len(s._outputs) != 1:
                raise MXNetError("input %s must be single-output" % arg)
            inputs.append(s._outputs[0])

    node_attrs = dict(scope_attrs)
    node_attrs.update(attrs)
    node = _Node(op, name, node_attrs, inputs)
    n_out = node._num_outputs
    return Symbol([(node, i) for i in range(n_out)])


def _make_sym_func(op: OpDef, fname: str):
    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        kwargs.pop("attr", None)
        sym_inputs = [a for a in args if isinstance(a, Symbol)]
        kw_syms = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        attrs = {k: v for k, v in kwargs.items()
                 if not isinstance(v, Symbol)}
        return _create(op.name, sym_inputs, attrs, name=name,
                       kw_inputs=kw_syms)

    fn.__name__ = fname
    fn.__doc__ = op.doc
    fn.__module__ = __name__
    return fn


def _install():
    mod = sys.modules[__name__]
    for key in OPS.keys():
        op = OPS.get(key)
        for alias in [op.name] + op.aliases:
            if not hasattr(mod, alias):
                setattr(mod, alias, _make_sym_func(op, alias))


_install()


# creation-op symbolic wrappers need explicit shape; install friendly names
def zeros(shape, dtype=None, **kwargs):
    return _create("_zeros", [], {"shape": shape,
                                  "dtype": dtype_name(dtype_np(dtype))},
                   name=kwargs.get("name"))


def ones(shape, dtype=None, **kwargs):
    return _create("_ones", [], {"shape": shape,
                                 "dtype": dtype_name(dtype_np(dtype))},
                   name=kwargs.get("name"))


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


def load_json(json_str: str) -> Symbol:
    """Parse a symbol JSON — current schema AND genuine pre-1.0
    reference files, applying the legacy upgrades of
    ``src/nnvm/legacy_json_util.cc``:

    - op params under the old ``param`` key (UpgradeJSON_Parse) and
      annotation attrs under ``attr`` (ctx_group/lr_mult/...) both
      merge into the node attrs;
    - pre-0.9 files omit aux-state variable inputs (e.g. BatchNorm's
      moving_mean/moving_var): missing trailing inputs are synthesized
      as ``<node>_<argname>`` variables carrying the node's attr dict
      (UpgradeJSON_000800_000900, legacy_json_util.cc:116-133);
    - ``argmin``/``argmax`` with the old ``axis="-1"`` sentinel drop
      the attr (int → optional<int>, UpgradeJSON_000904_000905).
    """
    data = json.loads(json_str)
    nodes_meta = data["nodes"]
    # built[i] maps the i-th JSON node (input/head indices refer to
    # these positions); synthesized legacy aux variables live outside
    built: List[_Node] = []
    for meta in nodes_meta:
        attrs = dict(meta.get("attrs", meta.get("param", {})) or {})
        # pre-1.0 annotation attrs live under "attr" (save_000800.json
        # fixture); op params win on key collisions
        for k, v in (meta.get("attr") or {}).items():
            attrs.setdefault(k, v)
        if meta["op"] == "null":
            node = _Node(None, meta["name"], attrs, [])
        else:
            op = get_op(meta["op"])
            if meta["op"] in ("argmin", "argmax") \
                    and attrs.get("axis") == "-1":
                attrs.pop("axis")
            inputs = [(built[i], idx) for i, idx, *_ in meta["inputs"]]
            # pre-0.9: synthesize missing trailing (aux) variable
            # inputs under their default names
            want = op.get_arg_names(attrs)
            if want is not None:
                full = list(want) + list(op.aux_names)
                for miss in range(len(inputs), len(full)):
                    var = _Node(None,
                                "%s_%s" % (meta["name"], full[miss]),
                                dict(attrs), [])
                    inputs.append((var, 0))
            node = _Node(op, meta["name"], attrs, inputs)
        built.append(node)
    heads = [(built[i], idx) for i, idx, *_ in data["heads"]]
    return Symbol(heads)


def load(fname: str) -> Symbol:
    # stream-URI dispatch like nd.load (the reference's Symbol::Load
    # went through dmlc Stream::Create too) — checkpoints pull whole
    # from http/s3/hdfs
    from ..filesystem import open_uri

    with open_uri(fname, "rb") as f:
        data = f.read()
    return load_json(data.decode("utf-8")
                     if isinstance(data, bytes) else data)
