"""Symbolic RNN cell zoo — ``mx.rnn``.

Reference analog: ``python/mxnet/rnn/rnn_cell.py`` (BaseRNNCell :108,
RNNCell :359, LSTMCell :405, GRUCell :466, FusedRNNCell :533,
SequentialRNNCell :745, DropoutCell :824, ModifierCell :864, Zoneout :906,
Residual :954, Bidirectional :995).

TPU-native notes: cells compose Symbols; ``unroll`` produces a static
graph the executor jits, so an unrolled cell and the fused ``mx.sym.RNN``
op (one ``lax.scan`` per layer) compile to the same XLA loop family.
Because XLA needs static shapes, a default ``begin_state`` is synthesized
*from the input symbol* (zeros broadcast against the batch dim) instead of
the reference's shape-0 placeholder trick.
"""
from __future__ import annotations

from .. import symbol
from ..base import MXNetError
from ..ops.rnn_ops import rnn_pack_weights, rnn_param_size, \
    rnn_unpack_weights

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


class RNNParams(object):
    """Container for shared cell parameters
    (reference ``rnn_cell.py:78``)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Split/merge between one (T,N,C)/(N,T,C) symbol and a list of T
    (N,C) symbols (reference ``rnn_cell.py:51``)."""
    assert inputs is not None
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            assert len(inputs.list_outputs()) == 1
            inputs = list(symbol.SliceChannel(inputs, axis=in_axis,
                                              num_outputs=length,
                                              squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=axis)
            in_axis = axis
    if isinstance(inputs, symbol.Symbol) and axis != in_axis:
        inputs = symbol.SwapAxis(inputs, dim1=axis, dim2=in_axis)
    return inputs, axis


def _zeros_like_state(sample, shape):
    """Zero state with the batch dim taken from ``sample`` (an (N, C) or
    (T, N, C) input symbol); ``shape`` has 0 in the batch position."""
    ndim = len(shape)
    if ndim == 2:
        # (0, H): (N,1) * (1,H)
        base = symbol.mean(sample, axis=-1, keepdims=True)
        zeros = symbol.zeros((1, shape[1]))
        return symbol.broadcast_mul(base * 0, zeros)
    if ndim == 3:
        # (L, 0, H) fused layout: sample is (T, N, C)
        base = symbol.mean(sample, axis=(0, 2), keepdims=True)  # (1,N,1)
        zeros = symbol.zeros((shape[0], 1, shape[2]))
        return symbol.broadcast_mul(base * 0, zeros)
    raise MXNetError("unsupported state ndim %d" % ndim)


def _cells_state_info(cells):
    return sum([c.state_info for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args


class BaseRNNCell(object):
    """Abstract cell: ``output, states = cell(inputs, states)``
    (reference ``rnn_cell.py:108``)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, batch_size=0, sample=None, **kwargs):
        """Initial states.  With ``func=None`` and a ``sample`` input
        symbol, synthesizes static-shape zeros from the sample; with
        ``batch_size`` given, materializes concrete zeros; or pass any
        ``func(name=..., shape=...)`` (e.g. ``sym.Variable``)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be " \
            "called directly. Call the modifier cell instead."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            shape = tuple(info["shape"])
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is not None:
                kw = dict(info)
                kw.pop("__layout__", None)
                kw.update(kwargs)
                states.append(func(name=name, **kw))
            elif sample is not None:
                states.append(_zeros_like_state(sample, shape))
            elif batch_size:
                concrete = tuple(batch_size if s == 0 else s
                                 for s in shape)
                states.append(symbol.zeros(concrete, name=name))
            else:
                states.append(symbol.Variable(name, shape=shape))
        return states

    def unpack_weights(self, args):
        """Split packed gate weights into per-gate entries
        (reference ``rnn_cell.py:222``)."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop("%s%s_weight" % (self._prefix, group_name))
            bias = args.pop("%s%s_bias" % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """Inverse of :meth:`unpack_weights`."""
        from ..ndarray import concatenate

        args = args.copy()
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = "%s%s%s_weight" % (self._prefix, group_name, gate)
                weight.append(args.pop(wname))
                bname = "%s%s%s_bias" % (self._prefix, group_name, gate)
                bias.append(args.pop(bname))
            args["%s%s_weight" % (self._prefix, group_name)] = \
                concatenate(weight)
            args["%s%s_bias" % (self._prefix, group_name)] = \
                concatenate(bias)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll ``length`` steps (reference ``rnn_cell.py:292``)."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(sample=inputs[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    @staticmethod
    def _get_activation(inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell: h' = act(W_i x + b_i + W_h h + b_h)
    (reference ``rnn_cell.py:359``)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference ``rnn_cell.py:405``); gate order i,f,c,o
    matches the fused RNN op packing."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias
        self._iB = self.params.get("i2h_bias",
                                   init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4,
                                          name="%sslice" % name)
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid",
                                    name="%si" % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid",
                                        name="%sf" % name)
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh",
                                         name="%sc" % name)
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid",
                                     name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference ``rnn_cell.py:466``); gate order r,z,n matches
    the fused RNN op packing (cuDNN order)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        seq_idx = self._counter
        name = "%st%d_" % (self._prefix, seq_idx)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(
            i2h, num_outputs=3, name="%si2h_slice" % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(
            h2h, num_outputs=3, name="%sh2h_slice" % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                       name="%sr_act" % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                        name="%sz_act" % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h,
                                       act_type="tanh",
                                       name="%sh_act" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + \
            update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN backed by the ``mx.sym.RNN`` op — one
    ``lax.scan`` per layer on TPU (reference ``rnn_cell.py:533`` wrapped
    cuDNN).  Weights live in one flat parameter vector."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0., get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._directions = 2 if bidirectional else 1
        self._parameter = self.params.get("parameters")

    @property
    def state_info(self):
        b = self._num_layers * self._directions
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden),
                 "__layout__": "LNC"}] * n

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def unpack_weights(self, args):
        """Flat fused vector → per-layer ``l%d_i2h%s_weight`` etc.
        entries (reference ``rnn_cell.py:636``)."""
        from ..ndarray import array

        args = args.copy()
        arr = args.pop(self._parameter.name)
        b = self._directions
        h = self._num_hidden
        input_size = self._input_size_from(arr)
        chunks = rnn_unpack_weights(arr.asnumpy(), self._mode,
                                    self._num_layers, input_size, h,
                                    self._bidirectional)
        gate_names = self._gate_names
        for idx, (wi, wh, bi, bh) in enumerate(chunks):
            layer = idx // b
            direction = idx % b
            p = "%s%s%d_" % (self._prefix,
                             "r" if direction else "l", layer)
            for j, gate in enumerate(gate_names):
                args["%si2h%s_weight" % (p, gate)] = array(
                    wi[j * h:(j + 1) * h])
                args["%sh2h%s_weight" % (p, gate)] = array(
                    wh[j * h:(j + 1) * h])
                args["%si2h%s_bias" % (p, gate)] = array(
                    bi[j * h:(j + 1) * h])
                args["%sh2h%s_bias" % (p, gate)] = array(
                    bh[j * h:(j + 1) * h])
        return args

    def pack_weights(self, args):
        import numpy as np

        from ..ndarray import array

        args = args.copy()
        b = self._directions
        h = self._num_hidden
        gate_names = self._gate_names
        chunks = []
        for layer in range(self._num_layers):
            for direction in range(b):
                p = "%s%s%d_" % (self._prefix,
                                 "r" if direction else "l", layer)
                wi = np.concatenate(
                    [args.pop("%si2h%s_weight" % (p, g)).asnumpy()
                     for g in gate_names])
                wh = np.concatenate(
                    [args.pop("%sh2h%s_weight" % (p, g)).asnumpy()
                     for g in gate_names])
                bi = np.concatenate(
                    [args.pop("%si2h%s_bias" % (p, g)).asnumpy()
                     for g in gate_names])
                bh = np.concatenate(
                    [args.pop("%sh2h%s_bias" % (p, g)).asnumpy()
                     for g in gate_names])
                chunks.append((wi, wh, bi, bh))
        flat = np.asarray(rnn_pack_weights(chunks, self._mode))
        args[self._parameter.name] = array(flat)
        return args

    def _input_size_from(self, arr):
        """Solve for input_size given the flat param vector length."""
        g = self._num_gates
        h = self._num_hidden
        L = self._num_layers
        d = self._directions
        total = arr.shape[0] if hasattr(arr, "shape") else len(arr)
        # total = d*g*h*input + (L-1)*d*(g*h*h*d) + L*d*g*h*h + 2*L*d*g*h
        rest = (L - 1) * d * g * h * h * d + L * d * g * h * h + \
            2 * L * d * g * h
        return (total - rest) // (d * g * h)

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped. Please use "
                         "unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:
            # RNN op wants TNC
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self.begin_state(sample=inputs)
        states = begin_state

        kwargs = dict(state_size=self._num_hidden,
                      num_layers=self._num_layers,
                      bidirectional=self._bidirectional,
                      p=self._dropout,
                      state_outputs=self._get_next_state,
                      mode=self._mode,
                      name=self._prefix + "rnn")
        if self._mode == "lstm":
            rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                             state=states[0], state_cell=states[1],
                             **kwargs)
        else:
            rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                             state=states[0], **kwargs)

        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]

        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs, _ = _normalize_sequence(
                length, outputs, layout, False,
                in_layout=layout)
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells
        (reference ``rnn_cell.py:711``)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix="%s_dropout%d_"
                    % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells (reference ``rnn_cell.py:745``)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child " \
                "cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)
        return self

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            if begin_state is None:
                # each child synthesizes zeros matching its own state
                # rank ((N,C) stepped cells vs (L,N,H) fused cells)
                states = None
            else:
                n = len(cell.state_info)
                states = begin_state[p:p + n]
                p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1
                else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout between stacked cells (reference ``rnn_cell.py:824``)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        assert isinstance(dropout, float)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if isinstance(inputs, symbol.Symbol):
            output, _ = self(inputs, [])
            return output, []
        outputs = [self(x, [])[0] for x in inputs]
        return outputs, []


class ModifierCell(BaseRNNCell):
    """Base for cells that wrap another cell
    (reference ``rnn_cell.py:864``)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference ``rnn_cell.py:906``): randomly
    keep previous states."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell does not support zoneout. Please unfuse first."
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell does not support zoneout since it " \
            "doesn't support step. Please add ZoneoutCell to the cells " \
            "underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = self.base_cell, \
            self.zoneout_outputs, self.zoneout_states
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: symbol.Dropout(
            symbol.ones_like(like), p=p)
        prev_output = self.prev_output if self.prev_output is not None \
            else next_output * 0
        output = symbol.where(mask(p_outputs, next_output), next_output,
                              prev_output) if p_outputs != 0. \
            else next_output
        states = [symbol.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """output = base(x) + x (reference ``rnn_cell.py:954``)."""

    def __init__(self, base_cell):
        super().__init__(base_cell)

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs,
                                     name="%s_plus_residual"
                                     % output.name)
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, symbol.Symbol) \
            if merge_outputs is None else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if merge_outputs:
            outputs = symbol.elemwise_add(outputs, inputs)
        else:
            outputs = [symbol.elemwise_add(out, inp)
                       for out, inp in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over the sequence
    (reference ``rnn_cell.py:995``)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params, \
                "Either specify params for BidirectionalCell or child " \
                "cells, not both."
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use "
                         "unroll")

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(sample=inputs[0])
        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info)],
            layout=layout, merge_outputs=merge_outputs)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):],
            layout=layout, merge_outputs=merge_outputs)

        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, symbol.Symbol) and \
                isinstance(r_outputs, symbol.Symbol)
            l_outputs, _ = _normalize_sequence(None, l_outputs, layout,
                                               merge_outputs)
            r_outputs, _ = _normalize_sequence(None, r_outputs, layout,
                                               merge_outputs)

        if merge_outputs:
            r_outputs = symbol.reverse(r_outputs, axis=axis)
            outputs = symbol.Concat(l_outputs, r_outputs, dim=2,
                                    name="%sout" % self._output_prefix)
        else:
            outputs = [symbol.Concat(l_o, r_o, dim=1,
                                     name="%st%d" % (self._output_prefix,
                                                     i))
                       for i, (l_o, r_o) in enumerate(
                           zip(l_outputs, reversed(r_outputs)))]
        states = l_states + r_states
        return outputs, states
