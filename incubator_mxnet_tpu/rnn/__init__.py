"""``mx.rnn`` — symbolic RNN cells, bucketed data io, checkpoints
(reference ``python/mxnet/rnn/``)."""
from .io import BucketSentenceIter, encode_sentences
from .rnn import (do_rnn_checkpoint, load_rnn_checkpoint,
                  save_rnn_checkpoint)
from .rnn_cell import (BaseRNNCell, BidirectionalCell, DropoutCell,
                       FusedRNNCell, GRUCell, LSTMCell, ModifierCell,
                       ResidualCell, RNNCell, RNNParams,
                       SequentialRNNCell, ZoneoutCell)
