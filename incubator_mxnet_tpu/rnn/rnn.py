"""RNN checkpoint helpers (reference ``python/mxnet/rnn/rnn.py``):
fused↔unfused weight conversion around the standard two-file checkpoint.
"""
from __future__ import annotations

from ..model import load_checkpoint, save_checkpoint

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def _as_cell_list(cells):
    if not isinstance(cells, (list, tuple)):
        return [cells]
    return list(cells)


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """Unpacks cell weights (fused vector → per-gate) before saving so
    checkpoints are portable across fused/unfused models
    (reference ``rnn/rnn.py:32``)."""
    cells = _as_cell_list(cells)
    for cell in cells:
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Inverse of :func:`save_rnn_checkpoint`
    (reference ``rnn/rnn.py:62``)."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    cells = _as_cell_list(cells)
    for cell in cells:
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback variant (reference ``rnn/rnn.py:97``)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
