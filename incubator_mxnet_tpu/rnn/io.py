"""RNN data io — ``encode_sentences`` + ``BucketSentenceIter``.

Reference analog: ``python/mxnet/rnn/io.py:30,78`` — same public surface
(``encode_sentences`` builds or extends a vocab while integer-coding
token lists; ``BucketSentenceIter`` pads variable-length sentences into
the smallest fitting bucket and yields language-model batches whose
``bucket_key`` is the padded length), reimplemented here: buckets are
padded as whole 2-D arrays rather than sentence-by-sentence, and the
next-token label shift happens once per bucket at ``reset``.
"""
from __future__ import annotations

import bisect
import logging
import random

import numpy as np

from .. import ndarray
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["encode_sentences", "BucketSentenceIter"]

logger = logging.getLogger(__name__)


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map tokenized sentences to lists of int ids.

    With ``vocab=None`` a fresh vocab is grown from the corpus (ids from
    ``start_label``, skipping ``invalid_label`` which is reserved for
    ``invalid_key`` / padding); a supplied vocab is read-only and an
    unknown token is an error.  Returns ``(coded_sentences, vocab)``
    (reference ``rnn/io.py:30``).
    """
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        frozen = False
    else:
        frozen = True
    next_id = start_label

    def lookup(word):
        nonlocal next_id
        if word not in vocab:
            if frozen:
                raise ValueError("unknown token %r not in supplied vocab"
                                 % (word,))
            if next_id == invalid_label:  # reserved for padding
                next_id += 1
            vocab[word] = next_id
            next_id += 1
        return vocab[word]

    coded = [[lookup(w) for w in sent] for sent in sentences]
    return coded, vocab


class BucketSentenceIter(DataIter):
    """Bucketed language-model iterator over integer-coded sentences.

    Each sentence lands in the smallest bucket that fits it, right-padded
    with ``invalid_label``; sentences longer than every bucket are
    dropped (logged).  Batches are whole slices of one bucket — data is
    the padded sentence, label the next-token shift — and carry
    ``bucket_key`` = that bucket's length for ``BucketingModule``.
    ``layout`` selects batch-major ``"NTC"`` (B, T) or time-major
    ``"TNC"`` (T, B) tensors (reference ``rnn/io.py:78``).
    """

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NTC"):
        super().__init__(batch_size)
        self.major_axis = DataDesc.get_batch_axis(layout)
        if self.major_axis not in (0, 1):
            raise ValueError("Invalid layout %s: must be NT (batch "
                             "major) or TN (time major)" % layout)

        lengths = [len(s) for s in sentences]
        if not buckets:
            # auto-buckets: every length that can fill at least one batch
            buckets = [length for length, n
                       in enumerate(np.bincount(lengths))
                       if n >= batch_size]
        buckets = sorted(buckets)

        # one padded (rows, bucket_len) array per bucket
        grouped = [[] for _ in buckets]
        ndiscard = 0
        for sent, n in zip(sentences, lengths):
            b = bisect.bisect_left(buckets, n)
            if b == len(buckets):
                ndiscard += 1
                continue
            grouped[b].append(sent)
        if ndiscard:
            logger.warning("discarded %d sentences longer than the "
                           "largest bucket.", ndiscard)
        # empty buckets can never yield a batch and would break the
        # 2-D label shift in reset — drop them outright
        self.buckets = [b for b, g in zip(buckets, grouped) if g]
        self.data = [self._pad(g, b, invalid_label, dtype)
                     for b, g in zip(buckets, grouped) if g]

        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.nddata = []
        self.ndlabel = []
        self.default_bucket_key = max(self.buckets)
        self.provide_data = [DataDesc(
            name=data_name,
            shape=self._batch_shape(self.default_bucket_key),
            layout=layout)]
        self.provide_label = [DataDesc(
            name=label_name,
            shape=self._batch_shape(self.default_bucket_key),
            layout=layout)]

        # (bucket, row-offset) pairs, one per full batch; partial
        # remainders never ship
        self.idx = [(i, j)
                    for i, rows in enumerate(self.data)
                    for j in range(0, len(rows) - batch_size + 1,
                                   batch_size)]
        self.curr_idx = 0
        self.reset()

    @staticmethod
    def _pad(sents, bucket_len, invalid_label, dtype):
        out = np.full((len(sents), bucket_len), invalid_label,
                      dtype=dtype)
        for row, sent in zip(out, sents):
            row[:len(sent)] = sent
        return out

    def _batch_shape(self, bucket_key):
        if self.major_axis == 0:  # batch major
            return (self.batch_size, bucket_key)
        return (bucket_key, self.batch_size)  # time major

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        self.nddata = []
        self.ndlabel = []
        for rows in self.data:
            np.random.shuffle(rows)
            # language-model target: the next token, padded at the end
            label = np.full_like(rows, self.invalid_label)
            label[:, :-1] = rows[:, 1:]
            self.nddata.append(ndarray.array(rows, dtype=self.dtype))
            self.ndlabel.append(ndarray.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1

        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        if self.major_axis == 1:  # time major: (B, T) -> (T, B)
            data, label = data.T, label.T

        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(name=self.data_name, shape=data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(name=self.label_name,
                                    shape=label.shape,
                                    layout=self.layout)])

    __next__ = next
