"""Testing utilities — the numeric contract of the reference test suite.

Reference analog: ``python/mxnet/test_utils.py`` — ``numeric_grad`` (:379),
``check_numeric_gradient`` (:439), ``check_symbolic_forward`` (:552),
``check_symbolic_backward`` (:617), ``check_consistency`` (:784),
``rand_ndarray``, ``assert_almost_equal``.  SURVEY.md §4: "the contract is
*numeric*, not structural" — ops vs numpy oracles, finite-difference
gradients, cross-context equivalence.

TPU adaptation of ``check_consistency``: the reference cross-compared
cpu/gpu/fp16 contexts.  Here the axes of variation are jax device kinds
(cpu host backend vs the TPU chip) and dtypes (float32 vs bfloat16/float16),
which exercises exactly what differs between compiled variants on TPU.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray import NDArray, array as nd_array

__all__ = [
    "default_context", "assert_almost_equal", "almost_equal", "same",
    "rand_shape_2d", "rand_shape_3d", "rand_shape_nd", "rand_ndarray",
    "random_arrays", "numeric_grad", "check_numeric_gradient",
    "check_symbolic_forward", "check_symbolic_backward",
    "check_consistency", "simple_forward",
]

_DEFAULT_RTOL = 1e-5
_DEFAULT_ATOL = 1e-20


def default_context() -> Context:
    return current_context()


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------


def _to_numpy(a) -> np.ndarray:
    if isinstance(a, NDArray):
        return a.asnumpy()
    if isinstance(a, np.ndarray):
        return a
    return np.asarray(a)


def same(a, b) -> bool:
    return np.array_equal(_to_numpy(a), _to_numpy(b))


def _find_max_violation(a, b, rtol, atol):
    error = np.abs(a - b) - atol - rtol * np.abs(b)
    if error.size == 0:
        return None, 0.0
    idx = tuple(int(i) for i in
                np.unravel_index(np.argmax(error), error.shape))
    return idx, error[idx]


def almost_equal(a, b, rtol=None, atol=None) -> bool:
    a, b = _to_numpy(a), _to_numpy(b)
    rtol = _DEFAULT_RTOL if rtol is None else rtol
    atol = _DEFAULT_ATOL if atol is None else atol
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    """Assert allclose with an error report pinpointing the worst element
    (reference ``assert_almost_equal`` / ``find_max_violation``)."""
    a, b = _to_numpy(a), _to_numpy(b)
    rtol = _DEFAULT_RTOL if rtol is None else rtol
    atol = _DEFAULT_ATOL if atol is None else atol
    if a.shape != b.shape:
        raise AssertionError("shape mismatch: %s %s vs %s %s"
                             % (names[0], a.shape, names[1], b.shape))
    if np.allclose(a.astype(np.float64), b.astype(np.float64),
                   rtol=rtol, atol=atol, equal_nan=True):
        return
    af, bf = a.astype(np.float64), b.astype(np.float64)
    idx, err = _find_max_violation(af, bf, rtol, atol)
    raise AssertionError(
        "Arrays not almost equal (rtol=%g atol=%g): max violation %g at "
        "index %s: %s=%r vs %s=%r" % (rtol, atol, err, idx,
                                      names[0], af[idx], names[1], bf[idx]))


# ---------------------------------------------------------------------------
# random data
# ---------------------------------------------------------------------------


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, ctx=None, dtype=np.float32, scale=1.0):
    return nd_array((np.random.uniform(-scale, scale, size=shape)
                     .astype(dtype)), ctx=ctx)


def random_arrays(*shapes, dtype=np.float32) -> List[np.ndarray]:
    arrays = [np.array(np.random.randn(), dtype=dtype) if len(s) == 0
              else np.random.randn(*s).astype(dtype) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


# ---------------------------------------------------------------------------
# location/expected normalization
# ---------------------------------------------------------------------------


def _parse_location(sym, location, ctx) -> Dict[str, np.ndarray]:
    arg_names = sym.list_arguments()
    if isinstance(location, dict):
        bad = set(location) - set(arg_names)
        if bad:
            raise MXNetError("location keys %s not in arguments %s"
                             % (sorted(bad), arg_names))
        loc = {k: _to_numpy(v) for k, v in location.items()}
    else:
        loc = {k: _to_numpy(v) for k, v in zip(arg_names, location)}
    return loc


def _parse_aux(sym, aux_states) -> Dict[str, np.ndarray]:
    aux_names = sym.list_auxiliary_states()
    if aux_states is None:
        return {}
    if isinstance(aux_states, dict):
        return {k: _to_numpy(v) for k, v in aux_states.items()}
    return {k: _to_numpy(v) for k, v in zip(aux_names, aux_states)}


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Forward a symbol with numpy inputs, return numpy outputs."""
    shapes = {k: v.shape for k, v in inputs.items()}
    exe = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
    exe.copy_params_from(inputs, allow_extra_params=True)
    outs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# numeric gradient
# ---------------------------------------------------------------------------


def numeric_grad(executor, location: Dict[str, np.ndarray],
                 aux_states=None, eps=1e-4,
                 use_forward_train=True) -> Dict[str, np.ndarray]:
    """Central finite differences of sum(outputs) w.r.t. each location
    entry (reference ``numeric_grad``, test_utils.py:379)."""

    def f_sum(name, vals):
        executor.copy_params_from({name: vals.astype(np.float32)},
                                  allow_extra_params=True)
        outs = executor.forward(is_train=use_forward_train) or \
            executor.outputs
        return sum(float(o.asnumpy().astype(np.float64).sum())
                   for o in outs)

    grads = {}
    for name, base in location.items():
        base = base.astype(np.float64).copy()
        grad = np.zeros_like(base)
        flat, gflat = base.reshape(-1), grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps / 2
            f_pos = f_sum(name, base)
            flat[i] = orig - eps / 2
            f_neg = f_sum(name, base)
            gflat[i] = (f_pos - f_neg) / eps
            flat[i] = orig
        f_sum(name, base)  # restore original values
        grads[name] = grad
    return grads


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, ctx=None):
    """Compare the symbol's compiled VJP gradients against central finite
    differences (reference ``check_numeric_gradient``, test_utils.py:439).

    The scalar objective is ``sum(out * random_proj)`` so every output
    element contributes with a distinct weight.
    """
    ctx = ctx or current_context()
    loc = _parse_location(sym, location, ctx)
    aux = _parse_aux(sym, aux_states)
    if grad_nodes is None:
        grad_nodes = list(loc.keys())

    # project each output with fixed random weights -> scalar loss
    from . import symbol as S

    proj_syms = []
    proj_vals = {}
    arg_shapes, out_shapes, _ = sym.infer_shape(
        **{k: v.shape for k, v in loc.items()})
    for i, oshape in enumerate(out_shapes):
        pname = "__random_proj_%d" % i
        proj_vals[pname] = np.random.normal(
            0, 0.1, size=oshape).astype(np.float32)
        proj_syms.append(
            S.sum(sym[i] * S.Variable(pname, shape=oshape)))
    out = proj_syms[0]
    for s in proj_syms[1:]:
        out = out + s

    grad_req = {n: ("write" if n in grad_nodes else "null")
                for n in out.list_arguments()}
    shapes = {k: v.shape for k, v in loc.items()}
    shapes.update({k: v.shape for k, v in proj_vals.items()})
    exe = out.simple_bind(ctx=ctx, grad_req=grad_req, **shapes)
    exe.copy_params_from(loc, allow_extra_params=True)
    exe.copy_params_from(proj_vals, allow_extra_params=True)
    if aux:
        exe.copy_params_from({}, aux)

    exe.forward(is_train=True)
    exe.backward()
    sym_grads = {n: exe.grad_dict[n].asnumpy() for n in grad_nodes
                 if n in exe.grad_dict}

    # numeric: finite differences of the same projected scalar (the bound
    # executor's single output IS the scalar, so numeric_grad's
    # sum-of-outputs objective matches the VJP's cotangent exactly)
    num_grads = numeric_grad(exe, {n: loc[n] for n in grad_nodes},
                             eps=numeric_eps)
    atol_eff = rtol if atol is None else atol
    for name in grad_nodes:
        assert_almost_equal(sym_grads[name], num_grads[name],
                            rtol=rtol, atol=atol_eff,
                            names=("symbolic_grad[%s]" % name,
                                   "numeric_grad[%s]" % name))


# ---------------------------------------------------------------------------
# symbolic forward/backward vs expected
# ---------------------------------------------------------------------------


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=None,
                           aux_states=None, ctx=None, is_train=False):
    """Bind, forward, compare each output to ``expected`` numpy arrays
    (reference test_utils.py:552)."""
    ctx = ctx or current_context()
    loc = _parse_location(sym, location, ctx)
    aux = _parse_aux(sym, aux_states)
    shapes = {k: v.shape for k, v in loc.items()}
    exe = sym.simple_bind(ctx=ctx, grad_req="null", **shapes)
    exe.copy_params_from(loc, aux or None, allow_extra_params=True)
    outs = exe.forward(is_train=is_train) or exe.outputs
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    for got, want, nm in zip(outs, expected, sym.list_outputs()):
        assert_almost_equal(got, want, rtol=rtol,
                            atol=(rtol if atol is None else atol),
                            names=("forward[%s]" % nm, "expected"))
    return [o.asnumpy() for o in outs]


def check_symbolic_backward(sym, location, out_grads, expected,
                            rtol=1e-5, atol=None, aux_states=None,
                            grad_req="write", ctx=None):
    """Bind with grads, forward+backward with given head grads, compare
    input grads to expected (reference test_utils.py:617)."""
    ctx = ctx or current_context()
    loc = _parse_location(sym, location, ctx)
    aux = _parse_aux(sym, aux_states)
    shapes = {k: v.shape for k, v in loc.items()}
    if isinstance(grad_req, str):
        req = {k: grad_req for k in sym.list_arguments()}
    else:
        req = dict(grad_req) if isinstance(grad_req, dict) else \
            dict(zip(sym.list_arguments(), grad_req))
    exe = sym.simple_bind(ctx=ctx, grad_req=req, **shapes)
    exe.copy_params_from(loc, aux or None, allow_extra_params=True)
    # seed 'add' grads with a known value to verify accumulation
    add_seed = {}
    for name, r in req.items():
        if r == "add" and name in exe.grad_dict:
            g = exe.grad_dict[name]
            seed = np.random.normal(size=g.shape).astype(np.float32)
            add_seed[name] = seed
            g._set_data(nd_array(seed, ctx=ctx).data)
    exe.forward(is_train=True)
    ogs = None
    if out_grads is not None:
        if isinstance(out_grads, dict):
            out_grads = [out_grads[k] for k in sym.list_outputs()]
        ogs = [nd_array(_to_numpy(g), ctx=ctx).data for g in out_grads]
    exe.backward(ogs)
    if isinstance(expected, dict):
        items = expected.items()
    else:
        items = zip(sym.list_arguments(), expected)
    grads = {}
    for name, want in items:
        if want is None:
            continue
        got = exe.grad_dict[name].asnumpy()
        want = _to_numpy(want)
        if name in add_seed:
            want = want + add_seed[name]
        assert_almost_equal(got, want, rtol=rtol,
                            atol=(rtol if atol is None else atol),
                            names=("grad[%s]" % name, "expected"))
        grads[name] = got
    return grads


# ---------------------------------------------------------------------------
# cross-variant consistency
# ---------------------------------------------------------------------------


def check_consistency(sym, ctx_list=None, dtypes=(np.float32, np.float16),
                      shapes=None, rtol=None, atol=None, scale=1.0,
                      grad_req="write", aux_states=None):
    """Run the same symbol under several variants and cross-compare outputs
    and gradients (reference ``check_consistency``, test_utils.py:784 —
    cpu vs gpu vs fp16 contexts).

    TPU adaptation: variants are dtypes (f32 vs bf16/f16) on the current
    device — the compiled-program axes that actually differ here.  The
    lowest-precision variant sets the tolerance, as in the reference.
    """
    if shapes is None:
        raise MXNetError("check_consistency requires input shapes")

    arg_names = sym.list_arguments()
    # randomize EVERY argument (weights included) with one shared draw so
    # the cross-variant comparison exercises the full compute path — the
    # reference seeds arg_params identically across contexts
    arg_shapes, _, _ = sym.infer_shape(**shapes)
    base = {n: np.random.normal(0, scale, size=s).astype(np.float64)
            for n, s in zip(arg_names, arg_shapes)}

    results = []
    for dt in dtypes:
        loc = {n: v.astype(np.float32) for n, v in base.items()}
        exe = sym.simple_bind(ctx=current_context(), grad_req=grad_req,
                              type_dict={n: dt for n in arg_names},
                              **{k: tuple(v) for k, v in shapes.items()})
        exe.copy_params_from(loc, allow_extra_params=True)
        exe.forward(is_train=True)
        exe.backward()
        results.append({
            "dtype": dt,
            "outputs": [o.asnumpy().astype(np.float64)
                        for o in exe.outputs],
            "grads": {n: g.asnumpy().astype(np.float64)
                      for n, g in exe.grad_dict.items()},
        })

    def _tol_for(dt):
        return 1e-1 if np.dtype(dt).itemsize <= 2 else 1e-3

    ref = results[0]
    for other in results[1:]:
        # lowest precision of the PAIR sets the tolerance
        t = rtol if rtol is not None else max(_tol_for(ref["dtype"]),
                                              _tol_for(other["dtype"]))
        a = atol if atol is not None else t
        for i, (x, y) in enumerate(zip(ref["outputs"], other["outputs"])):
            assert_almost_equal(x, y, rtol=t, atol=a,
                                names=("out%d[%s]" % (i, ref["dtype"]),
                                       "out%d[%s]" % (i, other["dtype"])))
        for n in ref["grads"]:
            assert_almost_equal(ref["grads"][n], other["grads"][n],
                                rtol=t, atol=a,
                                names=("grad[%s][%s]" % (n, ref["dtype"]),
                                       "grad[%s][%s]"
                                       % (n, other["dtype"])))
    return results
