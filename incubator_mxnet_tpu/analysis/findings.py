"""Finding records + ``# tp-lint`` suppression directives.

A finding anchors to either a source location (``file``/``line``, the
AST passes) or a graph node (``node``, the graph verifier — node names
carry ``name.py`` scope provenance).  Suppression is per-line::

    risky_call()  # tp-lint: disable=lock-held-blocking -- socket IO is
                  # serialized per-connection by design (Van semantics)

The ``-- justification`` tail is mandatory: a bare ``disable=`` is
itself reported as ``lint-bad-suppression``.  A directive on a line of
its own applies to the next source line.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["Finding", "load_suppressions", "filter_suppressed"]

_DIRECTIVE = re.compile(
    r"#\s*tp-lint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*))?$")


@dataclasses.dataclass
class Finding:
    """One reported violation."""

    rule: str
    message: str
    file: Optional[str] = None
    line: Optional[int] = None
    node: Optional[str] = None
    severity: str = "error"  # "error" | "warning"
    # stable identity of the subject (lock/attr/knob name) — combined
    # with rule + file it keys SARIF fingerprints across line churn
    ident: Optional[str] = None

    def location(self) -> str:
        if self.file is not None:
            loc = self.file
            if self.line is not None:
                loc += ":%d" % self.line
            return loc
        if self.node is not None:
            return "node '%s'" % self.node
        return "<global>"

    def render(self) -> str:
        return "%s: %s: [%s] %s" % (self.location(), self.severity,
                                    self.rule, self.message)

    def to_dict(self) -> Dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


def load_suppressions(path: str, source: Optional[str] = None,
                      ) -> Tuple[Dict[int, Set[str]], List[Finding]]:
    """Parse suppression directives out of one source file.

    Returns ``(line -> {rules}, problems)`` where *problems* are
    malformed directives (missing justification).  A directive whose
    line holds nothing but the comment suppresses the following line
    instead, so long rule names don't force 100-col lines.
    """
    if source is None:
        with open(path, "r") as f:
            source = f.read()
    by_line: Dict[int, Set[str]] = {}
    problems: List[Finding] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _DIRECTIVE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        justification = (m.group(2) or "").strip()
        if not justification:
            problems.append(Finding(
                rule="lint-bad-suppression",
                message="suppression of %s has no '-- justification' "
                        "tail; say why it is safe" % sorted(rules),
                file=path, line=lineno))
            continue
        target = lineno
        if text.lstrip().startswith("#"):
            target = lineno + 1
        by_line.setdefault(target, set()).update(rules)
        # a trailing directive also covers its own line when code
        # precedes the comment (target == lineno handled above)
        by_line.setdefault(lineno, set()).update(rules)
    return by_line, problems


def filter_suppressed(findings: List[Finding]) -> List[Finding]:
    """Drop findings whose file:line carries a matching directive; keep
    everything else (including graph-node findings, which have no file
    and therefore cannot be suppressed in source)."""
    cache: Dict[str, Dict[int, Set[str]]] = {}
    kept: List[Finding] = []
    for f in findings:
        if f.file is None or f.line is None:
            kept.append(f)
            continue
        if f.file not in cache:
            try:
                supp, _ = load_suppressions(f.file)
            except OSError:
                supp = {}
            cache[f.file] = supp
        rules = cache[f.file].get(f.line, ())
        if f.rule in rules or "all" in rules:
            continue
        kept.append(f)
    return kept
