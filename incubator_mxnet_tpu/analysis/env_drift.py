"""Env-knob drift pass: code ⟷ ``docs/env_var.md`` agreement.

Every ``TP_*`` variable the code reads (via :func:`base.get_env`, which
maps ``get_env("X")`` to ``TP_X``/``MXNET_X``, or via direct
``os.environ`` access) must appear in ``docs/env_var.md``; every
*exact* knob the doc lists must actually be read somewhere.  Glob rows
like ``TP_BENCH_*`` document a family and satisfy any matching read.

Rules: ``env-undocumented`` (read but absent from the doc),
``env-unread`` (documented but never read — stale doc), and
``env-default-drift`` (the doc's Default column disagrees with the
literal fallback at the read site).  Default comparison is best-effort:
only literal code defaults and simple doc cells (numbers, words,
``—`` for "no default") are compared; descriptive cells like
``2^19`` or derived formulas are skipped.
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List, Set, Tuple

from .findings import Finding

__all__ = ["check_env_drift", "collect_env_reads",
           "collect_documented", "collect_documented_defaults",
           "collect_read_defaults"]

_DOC_TOKEN = re.compile(r"\b(TP_[A-Z0-9_]+(?:_\*|\*)?)")
_SKIP_DIRS = {"tests", ".git", "__pycache__", ".claude"}


def collect_documented(doc_path: str) -> Tuple[Dict[str, int], Set[str]]:
    """(exact knob name -> doc line, glob patterns) listed in the doc."""
    with open(doc_path, "r") as f:
        lines = f.read().splitlines()
    exact: Dict[str, int] = {}
    globs: Set[str] = set()
    for lineno, line in enumerate(lines, start=1):
        for tok in _DOC_TOKEN.findall(line):
            if tok.endswith("*"):
                globs.add(tok)
            else:
                exact.setdefault(tok, lineno)
    return exact, globs


def collect_documented_defaults(doc_path: str) -> Dict[str, Tuple[str,
                                                                  int]]:
    """Exact knob name -> (Default-column cell, doc line).

    Parses the markdown tables: a row's first cell names the knob(s),
    its second cell is the documented default.  Rows naming several
    knobs (``TP_A / TP_B``) zip against a slash-separated default cell
    when the counts line up, else every name gets the whole cell.
    """
    with open(doc_path, "r") as f:
        lines = f.read().splitlines()
    out: Dict[str, Tuple[str, int]] = {}
    for lineno, line in enumerate(lines, start=1):
        if not line.lstrip().startswith("|"):
            continue
        cells = [c.strip().strip("`").strip()
                 for c in line.strip().strip("|").split("|")]
        if len(cells) < 2:
            continue
        names = [t for t in _DOC_TOKEN.findall(cells[0])
                 if not t.endswith("*")]
        if not names:
            continue
        defaults = [d.strip().strip("`").strip()
                    for d in cells[1].split("/")]
        if len(defaults) != len(names):
            defaults = [cells[1]] * len(names)
        for name, d in zip(names, defaults):
            out.setdefault(name, (d, lineno))
    return out


def _py_files(root: str) -> List[str]:
    out = []
    for base, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
        for f in files:
            if f.endswith(".py"):
                out.append(os.path.join(base, f))
    return sorted(out)


def collect_env_reads(repo_root: str) -> Dict[str, Tuple[str, int]]:
    """TP_* name -> (file, line) of one read site.

    Scans the package, ``tools/``, ``examples/`` and top-level entry
    scripts; ``tests/`` is excluded (tests *set* knobs, they don't
    define them).
    """
    roots = [os.path.join(repo_root, "incubator_mxnet_tpu"),
             os.path.join(repo_root, "tools"),
             os.path.join(repo_root, "examples")]
    files: List[str] = []
    for r in roots:
        if os.path.isdir(r):
            files.extend(_py_files(r))
    for f in os.listdir(repo_root):
        if f.endswith(".py"):
            files.append(os.path.join(repo_root, f))

    reads: Dict[str, Tuple[str, int]] = {}
    for path in files:
        with open(path, "r") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        rel = os.path.relpath(path, repo_root)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _dotted(node.func)
            arg = node.args[0] if node.args else None
            name = arg.value if isinstance(arg, ast.Constant) \
                and isinstance(arg.value, str) else None
            if fn is not None and fn.endswith("get_env") \
                    and name is not None:
                reads.setdefault("TP_" + name, (rel, node.lineno))
            elif fn in ("os.getenv", "os.environ.get",
                        "environ.get") and name is not None \
                    and name.startswith("TP_"):
                reads.setdefault(name, (rel, node.lineno))
        # os.environ["TP_X"], "TP_X" in os.environ, setdefault, etc. —
        # any literal TP_ constant in a non-test source counts as a use
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and re.fullmatch(r"TP_[A-Z0-9_]+", node.value):
                reads.setdefault(node.value, (rel, node.lineno))
    return reads


_NON_LITERAL = object()  # default exists but is not a literal constant


def collect_read_defaults(repo_root: str,
                          ) -> Dict[str, Tuple[str, int, object]]:
    """TP_* name -> (file, line, fallback) at one ``get_env`` /
    ``os.environ.get`` read site.

    The fallback is the literal constant passed as the default
    (``None`` when omitted), or ``_NON_LITERAL`` when it is a computed
    expression — those sites are skipped by the drift comparison.
    """
    roots = [os.path.join(repo_root, "incubator_mxnet_tpu"),
             os.path.join(repo_root, "tools"),
             os.path.join(repo_root, "examples")]
    files: List[str] = []
    for r in roots:
        if os.path.isdir(r):
            files.extend(_py_files(r))
    for f in os.listdir(repo_root):
        if f.endswith(".py"):
            files.append(os.path.join(repo_root, f))

    out: Dict[str, Tuple[str, int, object]] = {}

    def fallback(call, pos):
        node = None
        if len(call.args) > pos:
            node = call.args[pos]
        else:
            for kw in call.keywords:
                if kw.arg == "default":
                    node = kw.value
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            return node.value
        return _NON_LITERAL

    for path in files:
        with open(path, "r") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        rel = os.path.relpath(path, repo_root)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _dotted(node.func)
            arg = node.args[0] if node.args else None
            name = arg.value if isinstance(arg, ast.Constant) \
                and isinstance(arg.value, str) else None
            if name is None:
                continue
            if fn is not None and fn.endswith("get_env"):
                out.setdefault("TP_" + name,
                               (rel, node.lineno, fallback(node, 1)))
            elif fn in ("os.getenv", "os.environ.get", "environ.get") \
                    and name.startswith("TP_"):
                out.setdefault(name,
                               (rel, node.lineno, fallback(node, 1)))
    return out


_SIMPLE_CELL = re.compile(r"-?[A-Za-z0-9_.+\-]+$")
_NO_DEFAULT_CELLS = ("", "—", "-", "–", "none", "None", "unset",
                     "required")


def _defaults_match(doc_cell: str, code_default: object):
    """True/False when comparable, ``None`` when the doc cell is
    descriptive (a formula, a range) and no comparison is possible."""
    cell = doc_cell.strip().strip("`").strip()
    if cell in _NO_DEFAULT_CELLS:
        # an empty-string fallback is "no value" too
        return code_default is None or code_default == ""
    if not _SIMPLE_CELL.fullmatch(cell):
        return None  # descriptive cell — not comparable
    if code_default is None:
        return False
    if isinstance(code_default, bool):
        return cell == ("1" if code_default else "0") \
            or cell.lower() == str(code_default).lower()
    try:
        return float(cell) == float(code_default)
    except (TypeError, ValueError):
        return cell == str(code_default)


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def check_env_drift(repo_root: str,
                    doc_path: str = None) -> List[Finding]:
    doc_path = doc_path or os.path.join(repo_root, "docs",
                                        "env_var.md")
    exact, globs = collect_documented(doc_path)
    reads = collect_env_reads(repo_root)
    doc_rel = os.path.relpath(doc_path, repo_root)
    findings: List[Finding] = []

    def documented(name: str) -> bool:
        if name in exact:
            return True
        return any(fnmatch.fnmatch(name, g) for g in globs)

    for name, (file, line) in sorted(reads.items()):
        if not documented(name):
            findings.append(Finding(
                rule="env-undocumented",
                message="'%s' is read here but not documented in %s"
                        % (name, doc_rel),
                file=file, line=line))
    for name, doc_line in sorted(exact.items()):
        if name not in reads:
            findings.append(Finding(
                rule="env-unread",
                message="'%s' is documented in %s but nothing reads "
                        "it — stale doc or dead knob" % (name, doc_rel),
                file=doc_rel, line=doc_line, severity="warning"))

    doc_defaults = collect_documented_defaults(doc_path)
    code_defaults = collect_read_defaults(repo_root)
    for name, (cell, doc_line) in sorted(doc_defaults.items()):
        site = code_defaults.get(name)
        if site is None:
            continue  # env-unread already covers doc-only knobs
        file, line, fb = site
        if fb is _NON_LITERAL:
            continue  # computed fallback — nothing to compare
        ok = _defaults_match(cell, fb)
        if ok is False:
            findings.append(Finding(
                rule="env-default-drift",
                message="'%s' falls back to %r here but %s:%d "
                        "documents the default as '%s'"
                        % (name, fb, doc_rel, doc_line, cell),
                file=file, line=line, ident=name))
    return findings
