"""Pre-lowering Symbol-graph verifier.

Re-runs shape/dtype inference node-by-node over a built ``Symbol`` DAG
and reports structural defects *before* ``lower_symbol`` ever builds a
jax function — the role the reference's nnvm InferShape/InferType
passes played (``graph_executor.cc:826``), plus GSPMD-style trace-time
sharding validation when a mesh and partition specs are supplied.

Rules
-----
- ``graph-dangling-input``   edge references an output slot the producer
  does not have (or a node appears twice under one name)
- ``graph-shape-error``      per-node shape inference failed
- ``graph-dtype-mismatch``   two floating inputs of one node disagree
  (f32 meets f16 without an explicit Cast → silent upcast per step)
- ``graph-unused-output``    a multi-output node's slot is neither
  consumed nor a head (warning)
- ``graph-rank-losing-reshape``  Reshape collapses rank while moving the
  leading (batch) dim — the classic dp-sharding breaker (warning)
- ``graph-spec-unknown-axis`` / ``graph-spec-rank`` /
  ``graph-spec-indivisible``  partition spec names a missing mesh axis,
  exceeds the tensor rank, or shards a non-divisible dim
- ``graph-spec-conflict``    elementwise op joins inputs with different
  inferred specs (implicit resharding)
- ``graph-implicit-allgather``  contraction (FullyConnected/dot) over a
  sharded dim, or a Reshape merging a sharded axis — each forces an
  all-gather at compile time (warning)

Node provenance comes from node names, which carry ``name.py`` Prefix
scopes (``stage1_fc1`` etc.).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..base import dtype_np
from ..ops.registry import parse_tuple
from .findings import Finding

__all__ = ["verify_graph"]

# ops whose inputs must agree on floating dtype (joins of parallel
# branches — exactly where an accidental f16/f32 meet happens)
_ELEMWISE = {"elemwise_add", "elemwise_sub", "elemwise_mul",
             "elemwise_div", "add_n", "Concat", "concat",
             "broadcast_add", "broadcast_sub", "broadcast_mul",
             "broadcast_div", "_plus", "_minus", "_mul", "_div"}

_RESHAPE_OPS = {"Reshape", "reshape"}
_CONTRACTION_OPS = {"FullyConnected", "dot", "batch_dot"}


def _node_dtype(node, var_dtypes, out_dtypes):
    """Floating dtype flowing out of a node (None = unknown/int)."""
    if node.is_variable:
        return var_dtypes.get(node.name)
    return out_dtypes.get(id(node))


def verify_graph(symbol, shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                 dtypes: Optional[Dict[str, Any]] = None,
                 mesh_axes: Optional[Dict[str, int]] = None,
                 specs: Optional[Dict[str, Tuple]] = None) -> List[Finding]:
    """Statically verify one Symbol graph.

    ``shapes``/``dtypes`` seed leaf variables (same keys as
    ``infer_shape``); ``mesh_axes`` maps mesh axis name → size and
    ``specs`` maps variable name → PartitionSpec-like tuple of
    axis-name-or-None per dim (``("dp", None)``).
    """
    shapes = dict(shapes or {})
    findings: List[Finding] = []
    nodes = symbol.topo_nodes()
    heads = {(id(n), i) for n, i in symbol._outputs}

    # ---------------------------------------------------- structure
    seen_names: Dict[str, int] = {}
    consumed: Dict[int, set] = {}
    for node in nodes:
        # duplicate VARIABLE names are parameter sharing (the executor
        # feeds arrays by name) — only duplicate op names are suspicious
        if not node.is_variable:
            seen_names[node.name] = seen_names.get(node.name, 0) + 1
        for inp, idx in node.inputs:
            if idx >= inp._num_outputs or idx < 0:
                findings.append(Finding(
                    rule="graph-dangling-input",
                    message="input of '%s' references output %d of '%s' "
                            "which has only %d output(s)"
                            % (node.name, idx, inp.name,
                               inp._num_outputs),
                    node=node.name))
            consumed.setdefault(id(inp), set()).add(idx)
    for name, count in seen_names.items():
        if count > 1:
            findings.append(Finding(
                rule="graph-dangling-input",
                message="node name '%s' appears %d times — param "
                        "sharing by accident?" % (name, count),
                node=name))

    for node in nodes:
        if node.is_variable or node._num_outputs <= 1:
            continue
        used = consumed.get(id(node), set())
        for i in range(node._num_outputs):
            if i not in used and (id(node), i) not in heads:
                findings.append(Finding(
                    rule="graph-unused-output",
                    message="output %d of multi-output node '%s' (%s) "
                            "is never consumed"
                            % (i, node.name, node.op.name),
                    node=node.name, severity="warning"))

    # ----------------------------------------- shape + dtype + spec
    var_shapes: Dict[str, Optional[Tuple[int, ...]]] = {}
    node_shapes: Dict[Tuple[int, int], Optional[Tuple[int, ...]]] = {}
    var_dtypes: Dict[str, Any] = {}
    out_dtypes: Dict[int, Any] = {}
    # spec per (node id, out idx); None entries mean replicated dims
    entry_specs: Dict[Tuple[int, int], Optional[Tuple]] = {}
    dtypes = dict(dtypes or {})
    specs = dict(specs or {})
    mesh_axes = dict(mesh_axes or {})
    check_specs = bool(mesh_axes) or bool(specs)

    for node in nodes:
        if node.is_variable:
            s = shapes.get(node.name)
            if s is None:
                sa = node.attrs.get("__shape__")
                if sa is not None:
                    s = parse_tuple(sa)
            var_shapes[node.name] = tuple(s) if s is not None else None
            node_shapes[(id(node), 0)] = var_shapes[node.name]
            dt = dtypes.get(node.name, node.attrs.get("__dtype__"))
            if dt is not None:
                dt = np.dtype(dtype_np(dt))
                var_dtypes[node.name] = dt if dt.kind == "f" else None
            spec = specs.get(node.name)
            if check_specs and spec is not None:
                spec = tuple(spec)
                shp = var_shapes[node.name]
                for axis in spec:
                    if axis is not None and axis not in mesh_axes:
                        findings.append(Finding(
                            rule="graph-spec-unknown-axis",
                            message="spec %r of '%s' names mesh axis "
                                    "'%s' not in mesh %s"
                                    % (spec, node.name, axis,
                                       sorted(mesh_axes)),
                            node=node.name))
                if shp is not None:
                    if len(spec) > len(shp):
                        findings.append(Finding(
                            rule="graph-spec-rank",
                            message="spec %r of '%s' has %d entries for "
                                    "a rank-%d tensor"
                                    % (spec, node.name, len(spec),
                                       len(shp)),
                            node=node.name))
                    else:
                        for d, axis in enumerate(spec):
                            size = mesh_axes.get(axis)
                            if axis is None or size is None:
                                continue
                            if shp[d] % size != 0:
                                findings.append(Finding(
                                    rule="graph-spec-indivisible",
                                    message="dim %d of '%s' (%d) is not "
                                            "divisible by mesh axis "
                                            "'%s' (size %d)"
                                            % (d, node.name, shp[d],
                                               axis, size),
                                    node=node.name))
            entry_specs[(id(node), 0)] = spec
            continue

        in_shapes = []
        for inp, idx in node.inputs:
            if inp.is_variable:
                in_shapes.append(var_shapes.get(inp.name))
            else:
                in_shapes.append(node_shapes.get((id(inp), idx)))
        try:
            out_shapes = symbol._infer_node(node, in_shapes)
            backfill = list(getattr(symbol, "_last_in_shapes", in_shapes))
        except Exception as e:  # op rules raise MXNetError or ValueError
            findings.append(Finding(
                rule="graph-shape-error",
                message="shape inference failed at '%s' (%s): %s"
                        % (node.name, node.op.name, e),
                node=node.name))
            out_shapes = [None] * node._num_outputs
            backfill = in_shapes
        for i, s in enumerate(out_shapes):
            node_shapes[(id(node), i)] = s
        for (inp, idx), s in zip(node.inputs, backfill):
            if inp.is_variable and s is not None \
                    and var_shapes.get(inp.name) is None:
                var_shapes[inp.name] = tuple(s)
                node_shapes[(id(inp), 0)] = tuple(s)

        # dtype agreement among floating inputs of join ops
        in_dtypes = [_node_dtype(inp, var_dtypes, out_dtypes)
                     for inp, _ in node.inputs]
        floats = {dt for dt in in_dtypes if dt is not None}
        if node.op.name in _ELEMWISE and len(floats) > 1:
            pairs = ", ".join(
                "%s:%s" % (inp.name, dt)
                for (inp, _), dt in zip(node.inputs, in_dtypes)
                if dt is not None)
            findings.append(Finding(
                rule="graph-dtype-mismatch",
                message="'%s' (%s) joins inputs of different floating "
                        "dtypes (%s) — insert an explicit Cast"
                        % (node.name, node.op.name, pairs),
                node=node.name))
        if node.op.name in ("Cast", "cast", "amp_cast"):
            dt = node.attrs.get("dtype")
            out_dt = np.dtype(dtype_np(dt)) if dt is not None else None
            out_dtypes[id(node)] = \
                out_dt if out_dt is not None and out_dt.kind == "f" \
                else None
        elif floats:
            out_dtypes[id(node)] = max(floats,
                                       key=lambda d: d.itemsize)

        # rank-losing reshape that moves the batch dim — only a hazard
        # when the graph is being checked against a sharding context
        # ((B,T,C)->(B*T,C) is idiomatic for replicated seq models)
        if check_specs and node.op.name in _RESHAPE_OPS and node.inputs:
            ins = in_shapes[0]
            outs = out_shapes[0] if out_shapes else None
            if ins is not None and outs is not None \
                    and len(outs) < len(ins) and outs[0] != ins[0]:
                findings.append(Finding(
                    rule="graph-rank-losing-reshape",
                    message="'%s' reshapes %s -> %s, collapsing rank "
                            "across the leading (batch) dim"
                            % (node.name, tuple(ins), tuple(outs)),
                    node=node.name, severity="warning"))

        if check_specs:
            _propagate_specs(node, in_shapes, out_shapes, entry_specs,
                             findings)

    return findings


def _propagate_specs(node, in_shapes, out_shapes, entry_specs, findings):
    """Conservative spec propagation + all-gather/conflict detection."""
    in_specs = [entry_specs.get((id(inp), idx))
                for inp, idx in node.inputs]
    op = node.op.name

    nontrivial = [s for s in in_specs
                  if s is not None and any(a is not None for a in s)]
    if op in _ELEMWISE and len({s for s in in_specs
                                if s is not None}) > 1 and nontrivial:
        findings.append(Finding(
            rule="graph-spec-conflict",
            message="'%s' (%s) joins inputs with different partition "
                    "specs %s — implicit reshard at the join"
                    % (node.name, op,
                       [tuple(s) if s else None for s in in_specs]),
            node=node.name))

    if op in _CONTRACTION_OPS and in_specs and in_specs[0] is not None:
        data_spec = in_specs[0]
        data_shape = in_shapes[0]
        if data_shape is not None and len(data_spec) == len(data_shape):
            # FullyConnected/dot contract over the trailing data dim
            if data_spec[-1] is not None:
                findings.append(Finding(
                    rule="graph-implicit-allgather",
                    message="'%s' (%s) contracts over dim %d which is "
                            "sharded on axis '%s' — forces an "
                            "all-gather of the activations"
                            % (node.name, op, len(data_spec) - 1,
                               data_spec[-1]),
                    node=node.name, severity="warning"))

    if op in _RESHAPE_OPS and in_specs and in_specs[0] is not None:
        ins, outs = in_shapes[0], out_shapes[0] if out_shapes else None
        spec = in_specs[0]
        if ins is not None and outs is not None \
                and len(spec) == len(ins) and len(outs) != len(ins):
            sharded = [d for d, a in enumerate(spec) if a is not None]
            merged = [d for d in sharded
                      if d >= len(outs) or outs[d] != ins[d]]
            if merged:
                findings.append(Finding(
                    rule="graph-implicit-allgather",
                    message="'%s' reshapes %s -> %s merging sharded "
                            "dim(s) %s — forces an all-gather first"
                            % (node.name, tuple(ins), tuple(outs),
                               merged),
                    node=node.name, severity="warning"))

    # propagate: same-rank & same leading dim keeps the spec; leading
    # dim preserved keeps only the leading entry; otherwise replicated
    out = None
    if in_specs and in_specs[0] is not None and in_shapes \
            and in_shapes[0] is not None:
        spec, ins = in_specs[0], in_shapes[0]
        outs = out_shapes[0] if out_shapes else None
        if outs is not None and len(spec) == len(ins):
            if tuple(outs) == tuple(ins):
                out = tuple(spec)
            elif len(outs) == len(ins) and outs[0] == ins[0]:
                out = (spec[0],) + (None,) * (len(outs) - 1)
            elif outs and outs[0] == ins[0]:
                out = (spec[0],) + (None,) * (len(outs) - 1)
    for i in range(node._num_outputs):
        entry_specs[(id(node), i)] = out
