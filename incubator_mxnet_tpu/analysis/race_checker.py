"""Lockset data-race detector: static thread-role x lockset analysis
plus an opt-in runtime Eraser mode (``TP_RACE_CHECK=1``).

Static pass
-----------
For each class that owns background threads (``threading.Thread(
target=self._x)``, executor ``submit(self._x)``, timers), every entry
point is classified by *thread role*:

- ``thread:<m>``  the background-thread target methods
- ``api``         public methods + container dunders — the caller side
- ``final``       ``__del__`` / ``atexit`` / ``signal`` contexts
- ``init``        ``__init__`` (exempt until the first ``.start()``)

Each entry point's body is walked (following ``self.method()`` /
``self.attr.method()`` one level, the same resolution depth as
``lock_checker``) collecting every ``self.<attr>`` read/write together
with the lockset held at that site — lock identity is the
``Class.attr`` scheme shared with :mod:`.lock_checker`, including
member-object locks (``with self.stats.lock:`` resolves through the
``self.stats = ServeStats()`` attribute type).  Accesses through a
member of a known class unify on the *member's* identity
(``ServeStats.requests``), so ``self.stats.requests += 1`` in the
engine joins with ``self.requests`` accesses inside ``ServeStats``.

Rules:

- ``race-unlocked-shared-state``  an attribute reachable from >= 2
  thread roles with >= 1 write whose access locksets have an empty
  intersection — the Eraser lockset condition.  A *public* attribute
  written on a background thread with no lock held is also reported
  (external readers are an implicit unlocked role).
- ``race-check-then-act``   an ``if`` guard reads an attribute and a
  dependent write in the branch runs under a different (or no) lock —
  the state can change between test and act.
- ``race-init-escape``      ``__init__`` assigns an attribute *after*
  starting the background thread that reads it; the thread can observe
  the missing/partial value.  Assignments before the first
  ``.start()`` are exempt (single-threaded construction).

Thread-safe primitives (``Event``/``Queue``/``deque``/locks/
``Thread``) are exempt from mutation tracking: only *rebinding* such
an attribute counts as a write.

Runtime pass
------------
:func:`install_race_checker` extends the ``TP_LOCK_CHECK`` threading
patches' per-thread held-lock stacks with Eraser lockset refinement.
Classes marked with the :func:`race_audit` decorator get their
attribute access instrumented (``__getattribute__``/``__setattr__``):
each shared attribute starts *exclusive* to its first thread; from the
second thread on, its candidate lockset is intersected with the locks
held at every access, and the checker raises ``MXNetError`` carrying
both threads' stacks the moment the set empties after a shared-state
write.  The decorator is free when the checker is off — it only
registers the class.
"""
from __future__ import annotations

import ast
import sys
import threading
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..base import MXNetError
from .findings import Finding
from .lock_checker import _ClassInfo, _dotted, _scan_classes
from . import lock_checker as _lc

__all__ = ["analyze_race_files", "race_audit", "install_race_checker",
           "uninstall_race_checker", "race_checker_active"]

# types whose *internal* mutation is thread-safe: only rebinding the
# attribute races.  deque append/popleft are GIL-atomic by contract.
_THREADSAFE_TYPES = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "local", "Thread", "Timer",
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "deque",
    "ThreadPoolExecutor",
}

# method calls that mutate a plain container receiver
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse", "fill",
}

_API_DUNDERS = {"__iter__", "__next__", "__enter__", "__exit__",
                "__call__", "__len__", "__contains__", "__getitem__",
                "__setitem__"}


def _self_method(node: ast.AST) -> Optional[str]:
    d = _dotted(node)
    if d and d.startswith("self.") and d.count(".") == 1:
        return d[len("self."):]
    return None


def _scan_threadsafe(tree: ast.Module) -> Dict[str, Set[str]]:
    """Class name -> attrs assigned a thread-safe primitive type."""
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: Set[str] = set()
        out[node.name] = attrs
        for item in ast.walk(node):
            if not (isinstance(item, ast.Assign)
                    and isinstance(item.value, ast.Call)):
                continue
            ctor = _dotted(item.value.func) or ""
            if ctor.rsplit(".", 1)[-1] not in _THREADSAFE_TYPES:
                continue
            for tgt in item.targets:
                d = _dotted(tgt)
                if d and d.startswith("self.") and d.count(".") == 1:
                    attrs.add(d[len("self."):])
    return out


def _thread_roles(cls: _ClassInfo) -> Dict[str, Set[str]]:
    """Method name -> roles discovered from thread/finalizer wiring."""
    roles: Dict[str, Set[str]] = {}

    def mark(m: str, role: str):
        roles.setdefault(m, set()).add(role)

    for meth in cls.methods.values():
        for call in ast.walk(meth):
            if not isinstance(call, ast.Call):
                continue
            fn = _dotted(call.func) or ""
            tail = fn.rsplit(".", 1)[-1]
            cands: List[ast.AST] = []
            if tail in ("Thread", "Timer"):
                for kw in call.keywords:
                    if kw.arg in ("target", "function"):
                        cands.append(kw.value)
                if tail == "Timer" and len(call.args) >= 2:
                    cands.append(call.args[1])
            elif tail in ("submit", "apply_async", "run_in_executor",
                          "start_new_thread"):
                if call.args:
                    cands.append(call.args[0])
            elif fn in ("atexit.register", "weakref.finalize"):
                for a in list(call.args) \
                        + [kw.value for kw in call.keywords]:
                    m = _self_method(a)
                    if m:
                        mark(m, "final")
                continue
            elif fn == "signal.signal" and len(call.args) >= 2:
                m = _self_method(call.args[1])
                if m:
                    mark(m, "final")
                continue
            for cand in cands:
                m = _self_method(cand)
                if m:
                    mark(m, "thread:" + m)
                elif isinstance(cand, ast.Lambda):
                    for sub in ast.walk(cand.body):
                        if isinstance(sub, ast.Call):
                            sm = _self_method(sub.func)
                            if sm:
                                mark(sm, "thread:" + sm)
    if "__del__" in cls.methods:
        mark("__del__", "final")
    return roles


class _Access:
    __slots__ = ("attr", "write", "locks", "line", "role", "method")

    def __init__(self, attr, write, locks, line, role, method):
        self.attr = attr
        self.write = write
        self.locks = locks
        self.line = line
        self.role = role
        self.method = method


class _RaceWalker:
    """Collect self-attribute accesses + held locksets for one entry."""

    def __init__(self, path: str, classes: Dict[str, _ClassInfo],
                 ts: Dict[str, Set[str]], cls: _ClassInfo, role: str,
                 method: str, accesses: List[_Access],
                 cta: List[Tuple[_Access, _Access]], depth: int = 0):
        self.path = path
        self.classes = classes
        self.ts = ts
        self.cls = cls
        self.role = role
        self.method = method
        self.accesses = accesses
        self.cta = cta
        self.depth = depth

    # -------------------------------------------------- lock identity
    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        d = _dotted(expr)
        if d is None or not d.startswith("self."):
            return None
        parts = d.split(".")[1:]
        if len(parts) == 1 and parts[0] in self.cls.locks:
            return "%s.%s" % (self.cls.name, parts[0])
        if len(parts) == 2:
            t = self.cls.attr_types.get(parts[0])
            tc = self.classes.get(t) if t else None
            if tc is not None and parts[1] in tc.locks:
                return "%s.%s" % (tc.name, parts[1])
            # member of unknown type (e.g. aliased from another object):
            # a with-statement over a lock-named attribute is still a
            # lock acquisition with a stable per-class identity
            if parts[1] in ("lock", "_lock", "mutex", "_mutex",
                            "cond", "_cond"):
                return "%s.%s.%s" % (self.cls.name, parts[0], parts[1])
        return None

    def _self_path(self, node: ast.AST) -> Optional[Tuple[str, ...]]:
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name) and node.id == "self" and parts:
            parts.reverse()
            return tuple(parts[:2])
        return None

    # ------------------------------------------------------ recording
    def _record(self, path: Tuple[str, ...], write: bool,
                held: Tuple[str, ...], line: int):
        cls = self.cls
        a0 = path[0]
        if a0 in cls.locks or a0 in cls.methods:
            return
        if len(path) == 1:
            ident = "%s.%s" % (cls.name, a0)
        else:
            a1 = path[1]
            t = cls.attr_types.get(a0)
            tc = self.classes.get(t) if t else None
            if tc is not None:
                if a1 in tc.locks or a1 in tc.methods:
                    return
                ident = "%s.%s" % (tc.name, a1)
            else:
                ident = "%s.%s.%s" % (cls.name, a0, a1)
        self.accesses.append(_Access(ident, write, frozenset(held),
                                     line, self.role, self.method))

    # ----------------------------------------------------- statements
    def walk_body(self, body, held: Tuple[str, ...]):
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held: Tuple[str, ...]):
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                lid = self._lock_id(item.context_expr)
                if lid is not None:
                    inner = inner + (lid,)
                else:
                    self._expr(item.context_expr, inner)
                if item.optional_vars is not None:
                    self._write_target(item.optional_vars, inner)
            self.walk_body(stmt.body, inner)
            return
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value, held)
            for t in stmt.targets:
                self._write_target(t, held)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, held)
            self._write_target(stmt.target, held)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, held)
            self._write_target(stmt.target, held)
            return
        if isinstance(stmt, ast.If):
            n_test = len(self.accesses)
            self._expr(stmt.test, held)
            test_reads = [a for a in self.accesses[n_test:]
                          if not a.write]
            n_body = len(self.accesses)
            self.walk_body(stmt.body, held)
            body_accs = self.accesses[n_body:]
            for tr in test_reads:
                for w in body_accs:
                    if w.write and w.attr == tr.attr:
                        self.cta.append((tr, w))
                        break
            self.walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, held)
            self.walk_body(stmt.body, held)
            self.walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._write_target(stmt.target, held)
            self.walk_body(stmt.body, held)
            self.walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body, held)
            for h in stmt.handlers:
                self.walk_body(h.body, held)
            self.walk_body(stmt.orelse, held)
            self.walk_body(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: body runs later, not under the current locks
            self.walk_body(stmt.body, ())
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._write_target(t, held)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, held)

    # ---------------------------------------------------- expressions
    def _expr(self, node, held: Tuple[str, ...]):
        if node is None:
            return
        if isinstance(node, ast.Call):
            self._call(node, held)
            return
        if isinstance(node, ast.Attribute):
            path = self._self_path(node)
            if path is not None:
                self._record(path, False, held, node.lineno)
                return
            self._expr(node.value, held)
            return
        if isinstance(node, ast.Lambda):
            self._expr(node.body, ())
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child, held)

    def _call(self, node: ast.Call, held: Tuple[str, ...]):
        func = node.func
        if isinstance(func, ast.Attribute):
            path = self._self_path(func)
            if path is None:
                self._expr(func.value, held)
            elif len(path) == 1:
                m = path[0]
                if m in self.cls.methods:
                    self._follow(self.cls, m, held)
                else:
                    self._record((m,), False, held, node.lineno)
            else:
                recv, meth = path
                t = self.cls.attr_types.get(recv)
                tc = self.classes.get(t) if t else None
                if tc is not None and meth in tc.methods:
                    self._record((recv,), False, held, node.lineno)
                    self._follow(tc, meth, held)
                else:
                    threadsafe = recv in self.ts.get(self.cls.name, ())
                    write = meth in _MUTATORS and not threadsafe
                    self._record((recv,), write, held, node.lineno)
        else:
            self._expr(func, held)
        for a in node.args:
            self._expr(a, held)
        for kw in node.keywords:
            self._expr(kw.value, held)

    def _write_target(self, t, held: Tuple[str, ...]):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._write_target(e, held)
        elif isinstance(t, ast.Starred):
            self._write_target(t.value, held)
        elif isinstance(t, ast.Attribute):
            path = self._self_path(t)
            if path is not None:
                self._record(path, True, held, t.lineno)
            else:
                self._expr(t.value, held)
        elif isinstance(t, ast.Subscript):
            path = self._self_path(t.value) \
                if isinstance(t.value, ast.Attribute) else None
            if path is not None:
                self._record(path, True, held, t.lineno)
            else:
                self._expr(t.value, held)
            self._expr(t.slice, held)

    def _follow(self, target_cls: _ClassInfo, mname: str,
                held: Tuple[str, ...]):
        """One-level resolution, same depth cap as lock_checker."""
        if self.depth >= 2:
            return
        sub = _RaceWalker(self.path, self.classes, self.ts, target_cls,
                          self.role, self.method, self.accesses,
                          self.cta, depth=self.depth + 1)
        sub.walk_body(target_cls.methods[mname].body, held)


def _fmt_locks(locks: FrozenSet[str]) -> str:
    return "{%s}" % ", ".join(sorted(locks)) if locks else "no lock"


def _analyze_class(path: str, classes: Dict[str, _ClassInfo],
                   ts: Dict[str, Set[str]], cls: _ClassInfo,
                   findings: List[Finding]):
    troles = _thread_roles(cls)
    if not any(r.startswith("thread:")
               for rs in troles.values() for r in rs):
        return
    accesses: List[_Access] = []
    cta: List[Tuple[_Access, _Access]] = []

    entries: List[Tuple[str, str]] = []
    for m, rs in sorted(troles.items()):
        if m in cls.methods:
            for r in sorted(rs):
                entries.append((m, r))
    for m in sorted(cls.methods):
        if m == "__init__" or m in troles:
            continue
        if not m.startswith("_") or m in _API_DUNDERS:
            entries.append((m, "api"))
    for m, role in entries:
        w = _RaceWalker(path, classes, ts, cls, role, m, accesses, cta)
        w.walk_body(cls.methods[m].body, ())

    # ---- __init__: exempt until the first thread .start() ----------
    init = cls.methods.get("__init__")
    post_start_writes: List[_Access] = []
    start_line = None
    if init is not None:
        for c in ast.walk(init):
            if isinstance(c, ast.Call) \
                    and isinstance(c.func, ast.Attribute) \
                    and c.func.attr == "start" and not c.args:
                start_line = min(start_line or c.lineno, c.lineno)
        if start_line is not None:
            iacc: List[_Access] = []
            # depth=2 disables call-following so every access line is
            # physically inside __init__ and comparable to start_line
            w = _RaceWalker(path, classes, ts, cls, "init", "__init__",
                            iacc, [], depth=2)
            w.walk_body(init.body, ())
            post_start_writes = [a for a in iacc
                                 if a.write and a.line > start_line]

    thread_attrs = {a.attr for a in accesses
                    if a.role.startswith("thread:")}
    reported_ie: Set[str] = set()
    for a in post_start_writes:
        if a.attr in thread_attrs and a.attr not in reported_ie:
            reported_ie.add(a.attr)
            findings.append(Finding(
                rule="race-init-escape",
                message="'%s' is assigned in %s.__init__ at line %d "
                        "AFTER the background thread starts at line "
                        "%d; the thread can observe the attribute "
                        "missing or half-initialized — assign before "
                        ".start()" % (a.attr, cls.name, a.line,
                                      start_line),
                file=path, line=a.line, ident=a.attr))

    # ---- lockset intersection per attribute ------------------------
    by_attr: Dict[str, List[_Access]] = {}
    for a in accesses:
        by_attr.setdefault(a.attr, []).append(a)
    shared: Set[str] = set()
    unlocked_reported: Set[str] = set()
    for attr in sorted(by_attr):
        accs = by_attr[attr]
        roles = {a.role for a in accs}
        writes = [a for a in accs if a.write]
        if len(roles) >= 2 and writes:
            shared.add(attr)
            inter: Optional[FrozenSet[str]] = None
            for a in accs:
                inter = a.locks if inter is None else inter & a.locks
            if inter:
                continue
            pair = None
            for wacc in writes:
                for other in accs:
                    if other.role != wacc.role \
                            and not (wacc.locks & other.locks):
                        pair = (wacc, other)
                        break
                if pair:
                    break
            if pair is None:
                continue
            w0, o0 = pair
            findings.append(Finding(
                rule="race-unlocked-shared-state",
                message="'%s' is written in %s.%s() [%s] at line %d "
                        "holding %s and accessed in %s.%s() [%s] at "
                        "line %d holding %s — no common lock protects "
                        "it" % (attr, cls.name, w0.method, w0.role,
                               w0.line, _fmt_locks(w0.locks), cls.name,
                               o0.method, o0.role, o0.line,
                               _fmt_locks(o0.locks)),
                file=path, line=w0.line, ident=attr))
            unlocked_reported.add(attr)
        elif writes:
            # public attribute written on a background thread with no
            # lock: external readers are an implicit unlocked role
            public = not any(p.startswith("_")
                             for p in attr.split(".")[1:])
            tw = [a for a in writes
                  if a.role.startswith("thread:") and not a.locks]
            if public and tw:
                shared.add(attr)
                a0 = tw[0]
                findings.append(Finding(
                    rule="race-unlocked-shared-state",
                    message="public attribute '%s' is written in "
                            "%s.%s() on the %s thread at line %d with "
                            "no lock held; external readers can "
                            "observe torn or stale state"
                            % (attr, cls.name, a0.method, a0.role,
                               a0.line),
                    file=path, line=a0.line, ident=attr))
                unlocked_reported.add(attr)

    # ---- check-then-act --------------------------------------------
    seen_cta: Set[Tuple[str, int]] = set()
    for tr, wacc in cta:
        if tr.attr not in shared:
            continue
        if tr.locks & wacc.locks:
            continue
        if not tr.locks and not wacc.locks \
                and tr.attr in unlocked_reported:
            continue  # fully subsumed by race-unlocked-shared-state
        key = (tr.attr, tr.line)
        if key in seen_cta:
            continue
        seen_cta.add(key)
        findings.append(Finding(
            rule="race-check-then-act",
            message="guard on '%s' at line %d (%s) and the dependent "
                    "write at line %d (%s) in %s.%s() are not atomic "
                    "— the state can change between test and act"
                    % (tr.attr, tr.line, _fmt_locks(tr.locks),
                       wacc.line, _fmt_locks(wacc.locks), cls.name,
                       tr.method),
            file=path, line=tr.line, ident=tr.attr))


def analyze_race_files(paths: List[str]) -> List[Finding]:
    """Run the static lockset race pass over ``paths``."""
    findings: List[Finding] = []
    for path in paths:
        with open(path, "r") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                rule="race-parse-error", message=str(e), file=path,
                line=getattr(e, "lineno", 1) or 1))
            continue
        classes = _scan_classes(tree)
        ts = _scan_threadsafe(tree)
        for cls in classes.values():
            _analyze_class(path, classes, ts, cls, findings)
    # call-following can sight the same access through two entries —
    # collapse identical findings
    seen: Set[Tuple] = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.rule, f.file, f.line, f.ident or f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


# ===========================================================================
# runtime mode (TP_RACE_CHECK=1)
# ===========================================================================

_rt = None
_REGISTRY: List[type] = []
_EXEMPT_BY_CLASS: Dict[type, FrozenSet[str]] = {}


class _AttrState:
    __slots__ = ("threads", "lockset", "shared_write", "last")

    def __init__(self):
        self.threads: Set[int] = set()
        self.lockset = None         # None while exclusive to one thread
        self.shared_write = False   # write observed after going shared
        self.last: Dict[int, Tuple[str, Tuple[str, ...]]] = {}


class _RaceRuntime:
    def __init__(self, owns_lock_checker: bool):
        self.owns_lock_checker = owns_lock_checker
        # raw (unchecked) lock: the tracker must never feed back into
        # the lock-order checker's held stacks
        self.mutex = _lc._state.originals["Lock"]()
        self.reported: Set[Tuple[int, str]] = set()
        self.members: Dict[type, FrozenSet[str]] = {}
        self.exempt: Dict[type, FrozenSet[str]] = {}
        self.patched: List[Tuple] = []
        self.states: Dict[int, Dict] = {}  # fallback for __slots__


def _held_ids() -> FrozenSet[Tuple[int, str]]:
    st = _lc._state
    if st is None:
        return frozenset()
    return frozenset((id(l), l.site) for l in st.held())


def _short_stack(skip: int) -> Tuple[str, ...]:
    f = sys._getframe(skip)
    out = []
    while f is not None and len(out) < 6:
        out.append("%s:%d in %s" % (f.f_code.co_filename, f.f_lineno,
                                    f.f_code.co_name))
        f = f.f_back
    return tuple(out)


def _track(obj, name: str, is_write: bool):
    rt = _rt
    if rt is None or name.startswith("__") \
            or name.startswith("_tp_race"):
        return
    t = type(obj)
    members = rt.members.get(t)
    if members is None:
        members = rt.members[t] = frozenset(dir(t))
        ex: FrozenSet[str] = frozenset()
        for c in t.__mro__:
            ex = ex | _EXEMPT_BY_CLASS.get(c, frozenset())
        rt.exempt[t] = ex
    if name in members or name in rt.exempt[t]:
        return
    try:
        state = object.__getattribute__(obj, "_tp_race_state")
    except AttributeError:
        state = {}
        try:
            object.__setattr__(obj, "_tp_race_state", state)
        except AttributeError:
            state = rt.states.setdefault(id(obj), {})
    tid = threading.get_ident()
    held = _held_ids()
    stack = _short_stack(3)
    with rt.mutex:
        st = state.get(name)
        if st is None:
            st = state[name] = _AttrState()
        st.threads.add(tid)
        st.last[tid] = (threading.current_thread().name, stack)
        if len(st.threads) < 2:
            return  # exclusive: no refinement until a second thread
        st.lockset = held if st.lockset is None else st.lockset & held
        if is_write:
            st.shared_write = True
        if st.shared_write and not st.lockset:
            key = (id(obj), name)
            if key in rt.reported:
                return
            rt.reported.add(key)
            other = next((v for k, v in st.last.items() if k != tid),
                         ("?", ()))
            raise MXNetError(
                "data race on %s.%s (TP_RACE_CHECK): candidate "
                "lockset empty after multi-thread access with "
                "writes.\n  this thread (%s):\n    %s\n  other "
                "thread (%s):\n    %s"
                % (t.__name__, name, st.last[tid][0],
                   "\n    ".join(st.last[tid][1]), other[0],
                   "\n    ".join(other[1])))


def _patch_class(cls: type):
    if any("_tp_race_wrapped" in b.__dict__ for b in cls.__mro__):
        return  # a base is already instrumented — inherited
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__

    def __getattribute__(self, name):
        value = orig_get(self, name)
        _track(self, name, False)
        return value

    def __setattr__(self, name, value):
        orig_set(self, name, value)
        _track(self, name, True)

    had_get = "__getattribute__" in cls.__dict__
    had_set = "__setattr__" in cls.__dict__
    cls.__getattribute__ = __getattribute__
    cls.__setattr__ = __setattr__
    cls._tp_race_wrapped = True
    _rt.patched.append((cls, had_get, orig_get, had_set, orig_set))


def race_audit(cls=None, *, exempt=()):
    """Mark a threaded class for runtime race auditing.

    ``exempt`` lists attributes excluded from lockset refinement —
    by-design lock-free state (GIL-atomic monotonic flags, snapshot
    mirrors) that the static pass carries a written suppression for.
    Without ``TP_RACE_CHECK=1`` the decorator only records the class.
    """
    def deco(c):
        _EXEMPT_BY_CLASS[c] = _EXEMPT_BY_CLASS.get(c, frozenset()) \
            | frozenset(exempt)
        _REGISTRY.append(c)
        if _rt is not None:
            _patch_class(c)
            _rt.members.clear()
            _rt.exempt.clear()
        return c
    if cls is not None:
        return deco(cls)
    return deco


def install_race_checker():
    """Arm the Eraser-mode attribute tracker (idempotent).  Installs
    the ``TP_LOCK_CHECK`` lock patches too — the race checker reads
    its per-thread held stacks."""
    global _rt
    if _rt is not None:
        return
    from .lock_checker import (install_runtime_checker,
                               runtime_checker_active)
    owns = not runtime_checker_active()
    install_runtime_checker()
    _rt = _RaceRuntime(owns)
    for cls in list(_REGISTRY):
        _patch_class(cls)


def uninstall_race_checker():
    """Restore the audited classes' attribute access."""
    global _rt
    if _rt is None:
        return
    for cls, had_get, og, had_set, os_ in _rt.patched:
        if had_get:
            cls.__getattribute__ = og
        else:
            del cls.__getattribute__
        if had_set:
            cls.__setattr__ = os_
        else:
            del cls.__setattr__
        del cls._tp_race_wrapped
    owns = _rt.owns_lock_checker
    _rt = None
    if owns:
        from .lock_checker import uninstall_runtime_checker
        uninstall_runtime_checker()


def race_checker_active() -> bool:
    return _rt is not None
