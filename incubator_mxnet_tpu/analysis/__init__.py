"""Static-analysis suite — the pre-execution correctness passes the
reference got from nnvm graph passes, rebuilt for the jit-compiled world.

Three pass families (ISSUE 8):

- :mod:`.graph_verifier` — node-by-node shape/dtype re-inference over a
  built ``Symbol`` DAG plus mesh/partition-spec validation, *before*
  lowering ever touches XLA (the GSPMD trace-time-check pattern).
- :mod:`.tracing_lint` — Python-AST lint for host syncs and recompile
  hazards inside jitted code paths.
- :mod:`.lock_checker` — static lock-acquisition-order graph over the
  threaded modules, plus an opt-in runtime mode (``TP_LOCK_CHECK=1``)
  that wraps ``threading.Lock`` to assert one global order and flag
  held-lock blocking calls.
- :mod:`.env_drift` — every ``TP_*`` knob the code reads must appear in
  ``docs/env_var.md`` and vice versa, with matching documented
  defaults.
- :mod:`.race_checker` — Eraser-style lockset data-race detection over
  the threaded classes: static thread-role x lockset analysis plus an
  opt-in runtime mode (``TP_RACE_CHECK=1``) that instruments audited
  classes' attribute access and raises when a shared attribute's
  candidate lockset empties after multi-thread writes.

All passes report :class:`~.findings.Finding` records with file:line or
graph-node provenance, honoring ``# tp-lint: disable=<rule> -- why``
suppressions (see ``docs/static_analysis.md``).  ``tools/lint.py`` is
the CLI; ``tools/check.py`` runs it as a default-on gate.
"""
# Lazy (PEP 562): the runtime lock checker must be importable from the
# package __init__ before the op registry exists, and the graph pass
# pulls in jax — neither belongs on the default import path.
_EXPORTS = {
    "Finding": ("findings", "Finding"),
    "filter_suppressed": ("findings", "filter_suppressed"),
    "load_suppressions": ("findings", "load_suppressions"),
    "verify_graph": ("graph_verifier", "verify_graph"),
    "lint_tracing_file": ("tracing_lint", "lint_file"),
    "lint_tree": ("tracing_lint", "lint_tree"),
    "LockOrderGraph": ("lock_checker", "LockOrderGraph"),
    "analyze_lock_files": ("lock_checker", "analyze_lock_files"),
    "install_runtime_checker": ("lock_checker",
                                "install_runtime_checker"),
    "uninstall_runtime_checker": ("lock_checker",
                                  "uninstall_runtime_checker"),
    "runtime_checker_active": ("lock_checker",
                               "runtime_checker_active"),
    "check_env_drift": ("env_drift", "check_env_drift"),
    "analyze_race_files": ("race_checker", "analyze_race_files"),
    "race_audit": ("race_checker", "race_audit"),
    "install_race_checker": ("race_checker", "install_race_checker"),
    "uninstall_race_checker": ("race_checker",
                               "uninstall_race_checker"),
    "race_checker_active": ("race_checker", "race_checker_active"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(name)
    import importlib

    mod = importlib.import_module("." + mod_name, __name__)
    value = getattr(mod, attr)
    globals()[name] = value
    return value
