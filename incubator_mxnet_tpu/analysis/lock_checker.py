"""Lock-discipline checker: static acquisition-order graph + runtime
TSan-lite (``TP_LOCK_CHECK=1``).

Static pass
-----------
Parses the threaded modules, identifies lock objects created as
``self.X = threading.Lock()/RLock()/Condition()`` (lock identity =
``Class.attr``), and builds a global acquisition-order graph from
nested ``with`` blocks — following ``self.method()`` calls one level
deep so an outer lock held across a helper that takes another lock
still produces the edge.  Rules:

- ``lock-order-cycle``     two code paths acquire the same pair of
  locks in opposite orders (the AB/BA deadlock shape)
- ``lock-held-blocking``   a potentially unbounded blocking call runs
  while a lock is held: ``queue.get()``/``.join()`` without timeout,
  ``Thread.join()``, ``Future.result()`` without timeout,
  ``jax.device_get``/``.block_until_ready()``, ``time.sleep``, socket
  ``connect``/``recv``.  ``Condition.wait`` on the *held* condition is
  exempt (wait releases it).

Runtime pass
------------
:func:`install_runtime_checker` monkeypatches ``threading.Lock`` /
``RLock`` / ``Condition`` with creation-site-labeled proxies that
maintain a per-thread held stack, record every (outer → inner)
acquisition edge at site granularity, and raise ``MXNetError`` the
moment an inversion appears — on the *second* order, not on the
eventual deadlock.  It also wraps ``queue.Queue.get``/``join`` and
``jax.device_get`` to raise when called without a timeout while a
checked lock is held.  Production code never pays: the wrapping only
happens when ``TP_LOCK_CHECK=1`` and only affects locks created after
install.
"""
from __future__ import annotations

import ast
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..base import MXNetError
from .findings import Finding

__all__ = ["LockOrderGraph", "analyze_lock_files",
           "install_runtime_checker", "uninstall_runtime_checker",
           "runtime_checker_active"]

_LOCK_CTORS = {"threading.Lock", "threading.RLock",
               "threading.Condition", "Lock", "RLock", "Condition"}

# dotted/bare callables whose invocation can block on the network or
# the device for an unbounded time
_BLOCKING_SIMPLE = {"time.sleep", "jax.device_get", "_connect", "_rpc",
                    "_recv_msg", "_send_msg"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _has_timeout(call: ast.Call) -> bool:
    if any(kw.arg == "timeout" and not (
            isinstance(kw.value, ast.Constant)
            and kw.value.value is None)
           for kw in call.keywords):
        return True
    # queue.get(True, 5) positional timeout
    return len(call.args) >= 2


class LockOrderGraph:
    """Global acquisition-order graph accumulated across files."""

    def __init__(self):
        # (outer, inner) -> (file, line) of first sighting
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add(self, outer: str, inner: str, file: str, line: int):
        if outer == inner:
            return
        self.edges.setdefault((outer, inner), (file, line))

    def cycles(self) -> List[Finding]:
        findings = []
        seen: Set[Tuple[str, str]] = set()
        for (a, b), (fa, la) in sorted(self.edges.items()):
            if (b, a) in self.edges and (b, a) not in seen:
                fb, lb = self.edges[(b, a)]
                seen.add((a, b))
                findings.append(Finding(
                    rule="lock-order-cycle",
                    message="lock order inversion: '%s' -> '%s' at "
                            "%s:%d but '%s' -> '%s' at %s:%d"
                            % (a, b, fa, la, b, a, fb, lb),
                    file=fa, line=la))
        # longer cycles: DFS over the order graph
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(u):
            color[u] = 1
            stack.append(u)
            for v in adj.get(u, ()):
                if color.get(v, 0) == 1:
                    cyc = stack[stack.index(v):] + [v]
                    if len(cyc) > 3:  # 2-cycles reported above
                        site = self.edges[(u, v)]
                        findings.append(Finding(
                            rule="lock-order-cycle",
                            message="lock order cycle %s"
                                    % " -> ".join(cyc),
                            file=site[0], line=site[1]))
                elif color.get(v, 0) == 0:
                    dfs(v)
            stack.pop()
            color[u] = 2

        for u in list(adj):
            if color.get(u, 0) == 0:
                dfs(u)
        return findings


class _ClassInfo:
    def __init__(self, name):
        self.name = name
        self.locks: Dict[str, str] = {}      # attr -> kind
        self.attr_types: Dict[str, str] = {}  # attr -> ClassName
        self.methods: Dict[str, ast.FunctionDef] = {}


def _scan_classes(tree: ast.Module) -> Dict[str, _ClassInfo]:
    classes: Dict[str, _ClassInfo] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        info = _ClassInfo(node.name)
        classes[node.name] = info
        for item in ast.walk(node):
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods.setdefault(item.name, item)
            if isinstance(item, ast.Assign) \
                    and isinstance(item.value, ast.Call):
                ctor = _dotted(item.value.func)
                for tgt in item.targets:
                    d = _dotted(tgt)
                    if d is None or not d.startswith("self."):
                        continue
                    attr = d[len("self."):]
                    if ctor in _LOCK_CTORS:
                        info.locks[attr] = ctor.split(".")[-1]
                    elif ctor is not None and "." not in ctor:
                        info.attr_types[attr] = ctor
    return classes


class _MethodWalker:
    """Walk one method body tracking held locks; emit edges/findings."""

    def __init__(self, path: str, classes: Dict[str, _ClassInfo],
                 cls: _ClassInfo, graph: LockOrderGraph,
                 findings: List[Finding], depth: int = 0):
        self.path = path
        self.classes = classes
        self.cls = cls
        self.graph = graph
        self.findings = findings
        self.depth = depth

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        d = _dotted(expr)
        if d is None or not d.startswith("self."):
            return None
        attr = d[len("self."):]
        if attr in self.cls.locks:
            return "%s.%s" % (self.cls.name, attr)
        return None

    def walk_body(self, body, held: Tuple[str, ...]):
        for stmt in body:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt, held: Tuple[str, ...]):
        if isinstance(stmt, ast.With):
            inner_held = held
            for item in stmt.items:
                lock = self._lock_id(item.context_expr)
                if lock is not None:
                    for outer in inner_held:
                        self.graph.add(outer, lock, self.path,
                                       stmt.lineno)
                    inner_held = inner_held + (lock,)
                else:
                    self._scan_calls(item.context_expr, inner_held)
            self.walk_body(stmt.body, inner_held)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_calls(stmt.test, held)
            self.walk_body(stmt.body, held)
            self.walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, ast.For):
            self._scan_calls(stmt.iter, held)
            self.walk_body(stmt.body, held)
            self.walk_body(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body, held)
            for h in stmt.handlers:
                self.walk_body(h.body, held)
            self.walk_body(stmt.orelse, held)
            self.walk_body(stmt.finalbody, held)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: body runs later, not under the current locks
            self.walk_body(stmt.body, ())
            return
        self._scan_calls(stmt, held)

    # ---------------------------------------------------------- calls
    def _scan_calls(self, node: ast.AST, held: Tuple[str, ...]):
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            self._check_call(call, held)

    def _check_call(self, call: ast.Call, held: Tuple[str, ...]):
        d = _dotted(call.func)
        if d is None:
            return
        # explicit acquire() outside `with` — record edges only
        lock = self._lock_id(call.func.value) \
            if isinstance(call.func, ast.Attribute) else None
        if lock is not None and isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("acquire", "__enter__"):
            for outer in held:
                self.graph.add(outer, lock, self.path, call.lineno)
            return
        if not held:
            # still recurse into same-class helpers to find nested locks
            self._follow(call, held)
            return
        # blocking-call detection under a held lock
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            recv = _dotted(call.func.value) or ""
            if attr == "wait":
                # Condition.wait on the innermost held lock releases it
                if lock is not None and lock in held:
                    return
                if not _has_timeout(call):
                    self._blocking(call, held,
                                   "%s.wait() without timeout" % recv)
                return
            if attr in ("get", "join") and not _has_timeout(call):
                # str.join(...) takes an iterable arg; queue/thread
                # join() and queue get() are nullary-or-flag calls
                if attr == "join" and call.args:
                    return
                if attr == "get" and not self._queue_like(recv):
                    return
                if attr == "join" and not self._queue_like(recv) \
                        and not self._thread_like(recv):
                    return
                self._blocking(call, held,
                               "%s.%s() without timeout" % (recv, attr))
                return
            if attr == "result" and not _has_timeout(call):
                self._blocking(call, held,
                               "%s.result() without timeout" % recv)
                return
            if attr == "block_until_ready":
                self._blocking(call, held, "%s.block_until_ready()"
                               % recv)
                return
            if attr in ("connect", "recv", "accept", "_connect",
                        "_recv_msg", "recv_into", "sendall"):
                self._blocking(call, held, "socket %s.%s()"
                               % (recv, attr))
                return
        if d in _BLOCKING_SIMPLE:
            self._blocking(call, held, "%s()" % d)
            return
        self._follow(call, held)

    def _queue_like(self, recv: str) -> bool:
        r = recv.lower()
        return any(h in r for h in ("queue", "_q", ".q")) or r == "q"

    def _thread_like(self, recv: str) -> bool:
        r = recv.lower()
        return any(h in r for h in ("thread", "worker", "_t"))

    def _blocking(self, call, held, what):
        self.findings.append(Finding(
            rule="lock-held-blocking",
            message="%s while holding %s can stall every thread "
                    "contending for the lock" % (what, list(held)),
            file=self.path, line=call.lineno))

    def _follow(self, call: ast.Call, held: Tuple[str, ...]):
        """One-level resolution of self.method() / self.attr.method()."""
        if self.depth >= 2 or not isinstance(call.func, ast.Attribute):
            return
        d = _dotted(call.func)
        if d is None:
            return
        parts = d.split(".")
        if parts[0] != "self":
            return
        if len(parts) == 2 and parts[1] in self.cls.methods:
            target_cls, meth = self.cls, self.cls.methods[parts[1]]
        elif len(parts) == 3:
            tname = self.cls.attr_types.get(parts[1])
            tcls = self.classes.get(tname) if tname else None
            if tcls is None or parts[2] not in tcls.methods:
                return
            target_cls, meth = tcls, tcls.methods[parts[2]]
        else:
            return
        sub = _MethodWalker(self.path, self.classes, target_cls,
                            self.graph, self.findings,
                            depth=self.depth + 1)
        sub.walk_body(meth.body, held)


def analyze_lock_files(paths: List[str],
                       graph: Optional[LockOrderGraph] = None,
                       ) -> Tuple[List[Finding], LockOrderGraph]:
    """Run the static pass over ``paths``; returns (findings, graph)."""
    graph = graph or LockOrderGraph()
    findings: List[Finding] = []
    for path in paths:
        with open(path, "r") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                rule="lock-parse-error", message=str(e), file=path,
                line=getattr(e, "lineno", 1) or 1))
            continue
        classes = _scan_classes(tree)
        for cls in classes.values():
            for meth in cls.methods.values():
                walker = _MethodWalker(path, classes, cls, graph,
                                       findings)
                walker.walk_body(meth.body, ())
    findings.extend(graph.cycles())
    # the one-level call-following visits shared helpers once per
    # caller — collapse identical sightings
    seen: Set[Tuple] = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.rule, f.file, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique, graph


# ===========================================================================
# runtime mode (TP_LOCK_CHECK=1)
# ===========================================================================

_state = None


class _RuntimeState:
    def __init__(self):
        # capture originals FIRST: checked locks wrap these, so the
        # factories below never recurse through the patched names
        self.originals: Dict[str, object] = {
            "Lock": threading.Lock, "RLock": threading.RLock,
            "Condition": threading.Condition}
        self.tls = threading.local()
        self.mutex = self.originals["Lock"]()  # guards .edges
        # (outer site, inner site) -> "file:line of acquisition"
        self.edges: Dict[Tuple[str, str], str] = {}

    def held(self) -> List["_CheckedLock"]:
        st = getattr(self.tls, "stack", None)
        if st is None:
            st = self.tls.stack = []
        return st


def _call_site(skip: int = 2) -> str:
    import sys

    f = sys._getframe(skip)
    return "%s:%d" % (f.f_code.co_filename, f.f_lineno)


class _CheckedLock:
    """threading.Lock proxy asserting one global acquisition order."""

    def __init__(self, state: "_RuntimeState", site: str,
                 reentrant: bool = False):
        self._state = state
        self.site = site
        self._reentrant = reentrant
        mk = state.originals["RLock" if reentrant else "Lock"]
        self._lock = mk()

    # ---- order tracking -------------------------------------------
    def _note_acquired(self):
        state = self._state
        held = state.held()
        if self._reentrant and any(l is self for l in held):
            held.append(self)  # re-entry: no new edge
            return
        me = self.site
        with state.mutex:
            for outer in held:
                if outer is self:
                    continue
                a, b = outer.site, me
                if a == b:
                    continue
                state.edges.setdefault((a, b), _call_site(3))
                if (b, a) in state.edges:
                    raise MXNetError(
                        "lock order inversion: lock@%s then lock@%s "
                        "here, but lock@%s then lock@%s at %s "
                        "(TP_LOCK_CHECK)"
                        % (a, b, b, a, state.edges[(b, a)]))
        held.append(self)

    def _note_released(self):
        held = self._state.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                return

    # ---- Lock API --------------------------------------------------
    def acquire(self, blocking=True, timeout=-1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except BaseException:
                self._lock.release()
                raise
        return got

    def release(self):
        self._note_released()
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked() if hasattr(self._lock, "locked") \
            else False

    def _at_fork_reinit(self):
        # os.register_at_fork hook (bpo-39812): concurrent.futures
        # re-initializes its module locks in the forked child
        self._lock._at_fork_reinit()

    # Condition(_CheckedLock) support: python's Condition delegates to
    # these when present
    def _is_owned(self):
        return any(l is self for l in self._state.held())

    def _release_save(self):
        self._note_released()
        return self._lock.release()

    def _acquire_restore(self, saved):
        self._lock.acquire()
        self._state.held().append(self)


class _CheckedCondition(threading.Condition):
    """Condition over a checked lock; wait() correctly pops/pushes the
    held stack via the checked lock's _release_save/_acquire_restore."""

    def __init__(self, state: "_RuntimeState", site: str, lock=None):
        if lock is None:
            lock = _CheckedLock(state, site)
        super().__init__(lock)


def install_runtime_checker():
    """Patch threading lock constructors (idempotent).  Locks created
    *after* install are checked; existing locks are untouched."""
    global _state
    if _state is not None:
        return
    state = _RuntimeState()

    def make_lock():
        return _CheckedLock(state, _call_site())

    def make_rlock():
        return _CheckedLock(state, _call_site(), reentrant=True)

    def make_condition(lock=None):
        if lock is not None and not isinstance(lock, _CheckedLock):
            # foreign lock: fall back to a stock Condition
            return state.originals["Condition"](lock)
        return _CheckedCondition(state, _call_site(), lock)

    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Condition = make_condition

    # held-lock blocking detection: queue waits and device_get
    import queue as _queue

    def checked(name, orig, timeout_kw_ok=True):
        def wrapper(*args, **kwargs):
            blocking = True
            if name == "Queue.get":
                blocking = (args[1] if len(args) > 1
                            else kwargs.get("block", True))
            has_timeout = kwargs.get("timeout") is not None \
                or (name == "Queue.get" and len(args) > 2
                    and args[2] is not None)
            if blocking and not has_timeout and state.held():
                sites = [l.site for l in state.held()]
                raise MXNetError(
                    "%s without timeout while holding lock(s) %s "
                    "(TP_LOCK_CHECK): a blocked %s stalls every "
                    "contender" % (name, sites, name))
            return orig(*args, **kwargs)
        return wrapper

    state.originals["Queue.get"] = _queue.Queue.get
    state.originals["Queue.join"] = _queue.Queue.join
    _queue.Queue.get = checked("Queue.get", _queue.Queue.get)
    _queue.Queue.join = checked("Queue.join", _queue.Queue.join)
    try:
        import jax

        state.originals["jax.device_get"] = jax.device_get
        jax.device_get = checked("jax.device_get", jax.device_get)
    except ImportError:  # pragma: no cover - jax is a hard dep here
        pass

    _state = state


def uninstall_runtime_checker():
    """Restore the stock constructors.  Checked locks already handed
    out keep working (they wrap real locks)."""
    global _state
    if _state is None:
        return
    threading.Lock = _state.originals["Lock"]
    threading.RLock = _state.originals["RLock"]
    threading.Condition = _state.originals["Condition"]
    import queue as _queue

    _queue.Queue.get = _state.originals["Queue.get"]
    _queue.Queue.join = _state.originals["Queue.join"]
    if "jax.device_get" in _state.originals:
        import jax

        jax.device_get = _state.originals["jax.device_get"]
    _state = None


def runtime_checker_active() -> bool:
    return _state is not None
