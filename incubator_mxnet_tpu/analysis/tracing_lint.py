"""AST lint for host-sync and recompile hazards inside jitted code.

The jit boundary is this repo's bulk-exec segment: anything that forces
a host round-trip inside it (``.item()``, ``float()``, ``np.asarray``)
either raises a ConcretizationError at trace time or, worse, silently
syncs per step; env reads inside a traced function bake the value in at
trace time and recompile when it changes; a Python ``if`` on a tracer
recompiles per branch; reading a donated buffer after the jitted call
returns garbage.

Rules
-----
- ``trace-host-sync``      ``.item()`` / ``float()`` / ``int()`` /
  ``bool()`` / ``np.asarray`` / ``np.array`` on a traced value
- ``trace-env-read``       ``os.environ`` / ``os.getenv`` / ``get_env``
  inside a traced function body
- ``trace-python-branch``  ``if``/``while`` test on a bare tracer
  (``x.shape``-family attribute reads, ``is None`` checks,
  ``isinstance`` and ``len`` are trace-time-static and exempt)
- ``trace-donated-reuse``  a bare-name argument passed at a donated
  position of a ``donate_argnums`` jit is read again before being
  reassigned

Traced functions = defs decorated with ``jax.jit`` / ``partial(jax.jit,
...)``, defs passed to a ``jax.jit(...)`` call anywhere in the module,
and defs nested inside either.  ``static_argnums``/``static_argnames``
parameters are concrete and removed from the taint set.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding

__all__ = ["lint_file", "lint_tree"]

# attribute reads that are static under tracing (abstract-value metadata)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "nbytes", "itemsize", "at"}
_HOST_CASTS = {"float", "int", "bool", "complex"}
_NP_SYNCS = {"asarray", "array", "copy", "asnumpy"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this expression denote jax.jit (possibly via partial)?"""
    d = _dotted(node)
    if d in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fn = _dotted(node.func)
        if fn in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0])
        # jax.jit(f, ...) used as a decorator factory
        if fn in ("jax.jit", "jit"):
            return True
    return False


def _jit_call_info(call: ast.Call):
    """If ``call`` is ``jax.jit(...)`` return (fn_arg, static_names,
    donate_positions); else None."""
    if _dotted(call.func) not in ("jax.jit", "jit"):
        return None
    fn_arg = call.args[0] if call.args else None
    static: Set[int] = set()
    static_names: Set[str] = set()
    donate: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            static |= set(_int_tuple(kw.value))
        elif kw.arg == "static_argnames":
            static_names |= set(_str_tuple(kw.value))
        elif kw.arg == "donate_argnums":
            donate |= set(_int_tuple(kw.value))
    return fn_arg, static, static_names, donate


def _int_tuple(node) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, int)]
    return []


def _str_tuple(node) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _TracedBodyLinter(ast.NodeVisitor):
    """Lint one traced function body with a taint set of tracer names."""

    def __init__(self, path: str, fn: ast.AST, tainted: Set[str],
                 findings: List[Finding]):
        self.path = path
        self.fn = fn
        self.tainted = set(tainted)
        self.findings = findings

    def _emit(self, rule, node, msg):
        self.findings.append(Finding(
            rule=rule, message=msg, file=self.path, line=node.lineno))

    # -- taint propagation through simple assignments -------------------
    def visit_Assign(self, node: ast.Assign):
        self.generic_visit(node)
        rhs_tainted = bool(self._tainted_names(node.value))
        for tgt in node.targets:
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name):
                    if rhs_tainted:
                        self.tainted.add(n.id)
                    else:
                        self.tainted.discard(n.id)

    def _tainted_names(self, expr: ast.AST) -> Set[str]:
        """Tainted bare names in ``expr``, ignoring static-attr reads."""
        out: Set[str] = set()
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                continue
            if isinstance(n, ast.Name) and n.id in self.tainted:
                out.add(n.id)
        # drop names only reachable under static attrs / len / isinstance
        covered: Set[str] = set()
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                covered |= _names_in(n.value)
            if isinstance(n, ast.Call):
                fn = _dotted(n.func)
                if fn in ("isinstance", "len", "getattr", "hasattr",
                          "type"):
                    for a in n.args:
                        covered |= _names_in(a)
            if isinstance(n, ast.Compare):
                comps = [n.left] + list(n.comparators)
                if any(isinstance(o, (ast.Is, ast.IsNot))
                       for o in n.ops) and any(
                        isinstance(c, ast.Constant) and c.value is None
                        for c in comps):
                    for c in comps:
                        covered |= _names_in(c)
        return out - covered

    # -- host syncs ------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        fn = _dotted(node.func)
        # tainted.item() / .tolist() / .asnumpy()
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist", "asnumpy",
                                       "__float__"):
            if self._tainted_names(node.func.value):
                self._emit("trace-host-sync", node,
                           "'.%s()' on traced value '%s' forces a host "
                           "sync inside jit"
                           % (node.func.attr,
                              _dotted(node.func.value) or "<expr>"))
        elif fn in _HOST_CASTS and node.args \
                and self._tainted_names(node.args[0]):
            self._emit("trace-host-sync", node,
                       "'%s()' on a traced value concretizes inside "
                       "jit" % fn)
        elif fn in {"np.%s" % s for s in _NP_SYNCS} \
                | {"numpy.%s" % s for s in _NP_SYNCS} \
                | {"onp.%s" % s for s in _NP_SYNCS}:
            if node.args and self._tainted_names(node.args[0]):
                self._emit("trace-host-sync", node,
                           "'%s' on a traced value pulls it to host "
                           "inside jit" % fn)
        # env reads anywhere in a traced body
        if fn in ("os.getenv", "get_env", "base.get_env",
                  "os.environ.get"):
            self._emit("trace-env-read", node,
                       "'%s' inside a traced function is baked in at "
                       "trace time (recompile hazard)" % fn)

    def visit_Subscript(self, node: ast.Subscript):
        self.generic_visit(node)
        if _dotted(node.value) == "os.environ":
            self._emit("trace-env-read", node,
                       "'os.environ[...]' inside a traced function is "
                       "baked in at trace time (recompile hazard)")

    # -- python control flow on tracers ---------------------------------
    def _check_test(self, node, test):
        bad = self._tainted_names(test)
        if bad:
            self._emit("trace-python-branch", node,
                       "Python branch on traced value(s) %s — each "
                       "path recompiles; use jnp.where/lax.cond"
                       % sorted(bad))

    def visit_If(self, node: ast.If):
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp):
        self._check_test(node, node.test)
        self.generic_visit(node)

    # nested defs inherit taint via closure — lint them too
    def visit_FunctionDef(self, node: ast.FunctionDef):
        if node is self.fn:
            self.generic_visit(node)
            return
        sub = _TracedBodyLinter(self.path, node, self.tainted,
                                self.findings)
        params = [a.arg for a in node.args.posonlyargs + node.args.args]
        kwparams = [a.arg for a in node.args.kwonlyargs]
        # param taint comes from actual call sites; a function passed
        # by reference (lax.scan body, jax.grad target, ...) gets its
        # tracer arguments from jax, so everything is tainted
        call_funcs, calls, referenced = set(), [], False
        for n in ast.walk(self.fn):
            if isinstance(n, ast.Call):
                call_funcs.add(id(n.func))
                if isinstance(n.func, ast.Name) \
                        and n.func.id == node.name:
                    calls.append(n)
        for n in ast.walk(self.fn):
            if isinstance(n, ast.Name) and n.id == node.name \
                    and isinstance(n.ctx, ast.Load) \
                    and id(n) not in call_funcs:
                referenced = True
        tainted_params: Set[str] = set()
        if referenced or not calls:
            tainted_params = set(params) | set(kwparams)
        else:
            for c in calls:
                for i, a in enumerate(c.args):
                    if i < len(params) and self._tainted_names(a):
                        tainted_params.add(params[i])
                for kw in c.keywords:
                    if kw.arg and self._tainted_names(kw.value):
                        tainted_params.add(kw.arg)
        for p in params + kwparams:
            if p in tainted_params:
                sub.tainted.add(p)
            else:  # param shadows any tainted closure name
                sub.tainted.discard(p)
        for stmt in node.body:
            sub.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def _collect_traced_functions(tree: ast.Module):
    """(def node, static_names) for every function traced under jit."""
    # names passed to jax.jit(...) anywhere
    jit_by_name: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            info = _jit_call_info(node)
            if info is None:
                continue
            fn_arg, static, static_names, _donate = info
            if isinstance(fn_arg, ast.Name):
                jit_by_name[fn_arg.id] = (static, static_names)

    traced = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        static: Set[int] = set()
        static_names: Set[str] = set()
        is_traced = False
        for dec in node.decorator_list:
            if _is_jit_expr(dec):
                is_traced = True
                if isinstance(dec, ast.Call):
                    info = _jit_call_info(dec)
                    if info is None and _dotted(dec.func) in (
                            "partial", "functools.partial"):
                        for kw in dec.keywords:
                            if kw.arg == "static_argnums":
                                static |= set(_int_tuple(kw.value))
                            elif kw.arg == "static_argnames":
                                static_names |= set(
                                    _str_tuple(kw.value))
                    elif info is not None:
                        static |= info[1]
                        static_names |= info[2]
        if node.name in jit_by_name:
            is_traced = True
            s, sn = jit_by_name[node.name]
            static |= s
            static_names |= sn
        if is_traced:
            traced.append((node, static, static_names))
    return traced


def _lint_donated_reuse(path: str, tree: ast.Module,
                        findings: List[Finding]):
    """Flag reads of a bare-name donated argument after the jitted call."""
    # donating callables: name/attr assigned from jax.jit(..., donate_...)
    donators: Dict[str, Set[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            info = _jit_call_info(node.value)
            if info is None or not info[3]:
                continue
            for tgt in node.targets:
                d = _dotted(tgt)
                if d:
                    donators[d] = set(info[3])

    if not donators:
        return

    def scan_body(body):
        # name -> line where it became garbage
        donated: Dict[str, int] = {}
        for stmt in body:
            # reads in this statement, before processing its own call
            for n in ast.walk(stmt):
                if isinstance(n, ast.Name) and isinstance(n.ctx,
                                                          ast.Load) \
                        and n.id in donated:
                    call_line = donated[n.id]
                    # the donating call statement itself is exempt
                    if n.lineno > call_line:
                        findings.append(Finding(
                            rule="trace-donated-reuse",
                            message="'%s' was donated at line %d and "
                                    "its buffer is dead; reassign "
                                    "before reuse" % (n.id, call_line),
                            file=path, line=n.lineno))
                        del donated[n.id]
                        break
            # new donations from calls in this statement — recorded
            # BEFORE the reassignment check below, because in
            # ``p = step(p, g)`` the call consumes the old buffer and
            # the assignment rebinds ``p`` to the fresh one
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call):
                    d = _dotted(n.func)
                    if d in donators:
                        for pos in donators[d]:
                            if pos < len(n.args) and isinstance(
                                    n.args[pos], ast.Name):
                                donated[n.args[pos].id] = n.lineno
            # reassignment clears the poison
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            donated.pop(n.id, None)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                t = stmt.target
                if isinstance(t, ast.Name):
                    donated.pop(t.id, None)
        # names still poisoned at body end are fine (scope ends)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_body(node.body)


def lint_tree(path: str, tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    for fn, static, static_names in _collect_traced_functions(tree):
        args = fn.args
        tainted = {a.arg for a in args.args + args.kwonlyargs
                   + args.posonlyargs}
        if args.vararg:
            tainted.add(args.vararg.arg)
        tainted.discard("self")
        # static args are concrete python values, not tracers
        all_pos = [a.arg for a in args.posonlyargs + args.args]
        for i in static:
            if 0 <= i < len(all_pos):
                tainted.discard(all_pos[i])
        tainted -= static_names
        linter = _TracedBodyLinter(path, fn, tainted, findings)
        for stmt in fn.body:
            linter.visit(stmt)
    _lint_donated_reuse(path, tree, findings)
    return findings


def lint_file(path: str) -> List[Finding]:
    with open(path, "r") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(rule="trace-parse-error",
                        message="cannot parse: %s" % e, file=path,
                        line=getattr(e, "lineno", 1) or 1)]
    return lint_tree(path, tree)
