"""Server-role bootstrap (``python/mxnet/kvstore_server.py``).

When a process is launched with ``DMLC_ROLE=server`` (or ``scheduler``),
importing the package parks it in the serving loop instead of running the
training script — the reference's ``_init_kvstore_server_module`` contract
(kvstore_server.py:28-85).
"""
from __future__ import annotations

import os

from . import ps

__all__ = ["KVStoreServer", "init_server_module"]


class KVStoreServer:
    """Blocks the process in the server role (kvstore_server.py:30-70)."""

    def __init__(self, server_id=None):
        env = ps.node_env()
        self.env = env
        self.server_id = server_id if server_id is not None else \
            int(os.environ.get("TP_SERVER_ID", "0"))

    def run(self) -> None:
        env = self.env
        ps.bind_runtime()  # see ps.bind_runtime: no imports in handlers
        sched_addr = (env["scheduler_host"], env["scheduler_port"])
        server = ps.PSServer(self.server_id, env["num_workers"], sched_addr)
        server.register()
        server.run()


def _run_scheduler() -> None:
    env = ps.node_env()
    # bind the rendezvous address itself (DMLC_PS_ROOT_URI), never
    # 0.0.0.0: the transport unpickles peer messages, so the listener must
    # not be reachable beyond the cluster interface
    sched = ps.Scheduler(env["num_workers"], env["num_servers"],
                         host=env["scheduler_host"],
                         port=env["scheduler_port"])
    sched.start()
    sched._stopped.wait()


def init_server_module() -> bool:
    """Enter the server/scheduler loop if this process holds that role;
    returns True if it served (the caller should exit afterwards)."""
    role = os.environ.get("DMLC_ROLE", "worker")
    if role == "server":
        KVStoreServer().run()
        return True
    if role == "scheduler":
        _run_scheduler()
        return True
    return False
