"""Minimal load-and-forward inference entry — the C predict API analog.

Reference: ``src/c_api/c_predict_api.cc`` (``MXPredCreate`` :362,
``MXPredSetInput``, ``MXPredForward``, ``MXPredGetOutput``) — the
deployment surface that loads a symbol JSON + ``.params`` pair and runs
forward with NONE of the Module machinery.  TPU-native form: one
``jax.jit``-compiled forward closed over the loaded parameters, shapes
fixed at construction (the predict API fixed them at ``MXPredCreate``
too).

>>> p = Predictor.load("model-symbol.json", "model-0000.params",
...                    {"data": (1, 3, 224, 224)})
>>> out = p.predict(data=batch)[0]          # numpy, one call
>>> p.set_input(data=batch); p.forward()    # or the C-API 3-step form
>>> out = p.get_output(0)
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError

__all__ = ["Predictor"]


class Predictor:
    """Fixed-shape inference runner over a loaded symbol + params."""

    def __init__(self, symbol, arg_params, aux_params,
                 input_shapes: Dict[str, Sequence[int]],
                 input_dtypes: Optional[Dict[str, object]] = None):
        import jax

        from .lowering import lower_symbol

        self.symbol = symbol
        self._input_names = list(input_shapes.keys())
        # per-input staging dtypes (``MXPredCreateEx`` analog): token-id
        # inputs stay integral instead of round-tripping through f32
        self._dtypes = {n: np.dtype(d)
                        for n, d in (input_dtypes or {}).items()}
        for n in self._dtypes:
            if n not in input_shapes:
                raise MXNetError("input_dtypes names %r which is not an "
                                 "input (declared: %s)"
                                 % (n, self._input_names))
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        for n in self._input_names:
            if n not in arg_names:
                raise MXNetError("input %r is not an argument of the "
                                 "symbol" % (n,))
        shapes = {n: tuple(s) for n, s in input_shapes.items()}
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        shape_of = dict(zip(arg_names, arg_shapes))

        def park(src, name, shape):
            v = src.get(name)
            if v is None:
                # label inputs of loss heads are dead at inference
                # (SoftmaxOutput forward ignores them); the C predict
                # API bound them to dummy zeros the same way
                if "label" in name:
                    return jax.device_put(
                        np.zeros(shape, dtype=np.float32))
                raise MXNetError("missing parameter %r" % (name,))
            a = np.asarray(v.data if hasattr(v, "data") else v)
            if a.dtype == np.float64:
                a = a.astype(np.float32)  # jax default-f32 convention
            if tuple(a.shape) != tuple(shape):
                raise MXNetError(
                    "parameter %r has shape %s, expected %s"
                    % (name, a.shape, tuple(shape)))
            return jax.device_put(a)

        arg_params = arg_params or {}
        aux_params = aux_params or {}
        self._params = {n: park(arg_params, n, shape_of[n])
                        for n in arg_names if n not in shapes}
        self._aux = {n: park(aux_params, n, s)
                     for n, s in zip(aux_names, aux_shapes)}
        self._shapes = shapes

        fwd = lower_symbol(symbol, is_train=False)
        key = jax.random.PRNGKey(0)
        params = self._params
        aux = self._aux

        def run(inputs):
            args = dict(params)
            args.update(inputs)
            outs, _ = fwd(args, aux, key)
            return outs

        self._run = jax.jit(run)
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Optional[List] = None

    # ------------------------------------------------------------ build
    @classmethod
    def load(cls, symbol_file: str, param_file: str,
             input_shapes: Dict[str, Sequence[int]],
             input_dtypes: Optional[Dict[str, object]] = None
             ) -> "Predictor":
        """``MXPredCreate`` from the two-file checkpoint: symbol JSON +
        ``.params`` with ``arg:``/``aux:`` prefixed names (the format
        ``model.save_checkpoint`` and the reference both write)."""
        from . import ndarray as nd
        from . import symbol as sym

        net = sym.load(symbol_file)
        saved = nd.load(param_file)
        if not isinstance(saved, dict):
            raise MXNetError("%s holds an unnamed array list, not a "
                             "checkpoint" % param_file)
        arg_params, aux_params = {}, {}
        for k, v in saved.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:  # bare names: accept as args (predict API did)
                arg_params[k] = v
        return cls(net, arg_params, aux_params, input_shapes,
                   input_dtypes=input_dtypes)

    # ------------------------------------------------------- C-API form
    def set_input(self, **inputs) -> None:
        """``MXPredSetInput``: stage named input arrays.

        An explicitly declared dtype (``input_dtypes``) always wins —
        quantized checkpoints can declare int8/uint8 inputs and they
        reach the graph untouched.  Undeclared inputs get the default
        mapping: integer/bool arrays stay integral (64-bit narrows to
        32 for the jax default-x32 config), floats land on f32."""
        for n, v in inputs.items():
            if n not in self._shapes:
                raise MXNetError("unknown input %r (declared: %s)"
                                 % (n, self._input_names))
            a = np.asarray(v.data if hasattr(v, "data") else v)
            want = self._dtypes.get(n)
            if want is not None:
                a = a.astype(want, copy=False)
            elif a.dtype == np.int64:
                a = a.astype(np.int32)
            elif a.dtype == np.uint64:
                a = a.astype(np.uint32)
            elif a.dtype.kind not in "iub":
                a = a.astype(np.float32, copy=False)
            if tuple(a.shape) != self._shapes[n]:
                raise MXNetError("input %r has shape %s, expected %s"
                                 % (n, a.shape, self._shapes[n]))
            self._inputs[n] = a
        self._outputs = None

    def forward(self) -> None:
        """``MXPredForward``: run the compiled forward."""
        missing = [n for n in self._input_names if n not in self._inputs]
        if missing:
            raise MXNetError("inputs not set: %s" % missing)
        self._outputs = list(self._run(self._inputs))

    def get_output(self, index: int = 0) -> np.ndarray:
        """``MXPredGetOutput``: fetch output ``index`` as numpy."""
        if self._outputs is None:
            raise MXNetError("call forward() first")
        return np.asarray(self._outputs[index])

    # ----------------------------------------------------- one-call form
    def predict(self, **inputs) -> List[np.ndarray]:
        self.set_input(**inputs)
        self.forward()
        return [np.asarray(o) for o in self._outputs]
