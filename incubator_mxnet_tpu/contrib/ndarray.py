"""``mx.contrib.nd`` — contrib ops with the ``_contrib_`` prefix stripped.

Reference analog: ``python/mxnet/contrib/ndarray.py``.
"""
from __future__ import annotations

import sys

from ..ops.registry import OPS
from .. import ndarray as _ndarray


def _install():
    mod = sys.modules[__name__]
    for key in OPS.keys():
        if not key.startswith("_contrib_"):
            continue
        short = key[len("_contrib_"):]
        fn = getattr(_ndarray, key, None)
        if fn is not None and not hasattr(mod, short):
            setattr(mod, short, fn)


_install()
