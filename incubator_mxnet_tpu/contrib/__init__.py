"""Experimental contributions (``python/mxnet/contrib/__init__.py``)."""
from . import symbol
from . import ndarray

from . import symbol as sym
from . import ndarray as nd

from . import autograd
from . import tensorboard
