"""Experimental autograd aliases (``python/mxnet/contrib/autograd.py``).

The contrib module predates the stable ``mx.autograd``; it re-exports the
same machinery under the old names.
"""
from ..autograd import (record as train_section,  # noqa: F401
                        pause as test_section,  # noqa: F401
                        mark_variables, backward,  # noqa: F401
                        set_recording as set_is_training)  # noqa: F401


def compute_gradient(outputs):
    """Compute gradients of outputs w.r.t. marked variables
    (contrib/autograd.py:50)."""
    backward(outputs)
