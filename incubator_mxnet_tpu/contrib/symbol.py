"""``mx.contrib.sym`` — contrib ops with the ``_contrib_`` prefix stripped.

Reference analog: ``python/mxnet/contrib/symbol.py`` (an empty namespace the
C registry populates with every op whose name starts ``_contrib_``).
"""
from __future__ import annotations

import sys

from ..ops.registry import OPS
from .. import symbol as _symbol


def _install():
    mod = sys.modules[__name__]
    for key in OPS.keys():
        if not key.startswith("_contrib_"):
            continue
        short = key[len("_contrib_"):]
        if not hasattr(mod, short):
            setattr(mod, short, getattr(_symbol, key))


_install()
