"""TensorBoard logging callback (``python/mxnet/contrib/tensorboard.py``).

Writes metric scalars through an available summary-writer backend; if no
tensorboard package is importable (this image ships none), the callback
degrades to logging so training scripts keep running.
"""
from __future__ import annotations

import logging


class LogMetricsCallback(object):
    """Log metrics periodically in TensorBoard (batch-end callback).

    Mirrors contrib/tensorboard.py:45-76: on every callback with a metric,
    write one scalar per (name, value) pair.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = None
        writer_cls = None
        try:  # dmlc tensorboard package
            from tensorboard import SummaryWriter as writer_cls  # noqa: F401
        except ImportError:
            try:  # torch's writer as a stand-in
                from torch.utils.tensorboard import (  # noqa: F401
                    SummaryWriter as writer_cls)
            except Exception:
                writer_cls = None
        if writer_cls is not None:
            try:
                self.summary_writer = writer_cls(logging_dir)
            except Exception:
                self.summary_writer = None
        if self.summary_writer is None:
            logging.warning(
                "tensorboard is not available; LogMetricsCallback will "
                "log scalars via logging instead")

    def __call__(self, param):
        """Callback to log training speed and metrics in TensorBoard."""
        if param.eval_metric is None:
            return
        name_value = param.eval_metric.get_name_value()
        for name, value in name_value:
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            if self.summary_writer is not None:
                self.summary_writer.add_scalar(name, value)
            else:
                logging.info("tensorboard scalar %s=%s", name, value)
