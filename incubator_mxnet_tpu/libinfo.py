"""Library discovery + version (reference ``python/mxnet/libinfo.py``).

The reference located ``libmxnet.so``; here the native component is the
on-demand-built ``_native.so`` (recordio scanner / batch assembler) and
the compute library is jax itself.
"""
from __future__ import annotations

import os
from typing import List

__version__ = "0.11.0.tp3"  # tracks the reference API version + round


def find_lib_path() -> List[str]:
    """Paths of the native libraries this build uses (may be empty when
    the C++ toolchain is unavailable — every native piece has a python
    fallback)."""
    from . import native

    paths = []
    if native.lib() is not None:
        paths.append(os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "_native.so"))
    return paths
