"""Symbol → pure-jax-function lowering, shared by the Executor and the
fused parallel train step (single source of truth for op apply / aux
write-back / RNG-key folding semantics)."""
from __future__ import annotations

from .ops.registry import OpContext

__all__ = ["lower_symbol"]


def lower_symbol(symbol, is_train: bool, group2ctx=None):
    """Lower a Symbol DAG to ``fn(arg_vals, aux_vals, key) ->
    (outputs, new_aux)``.

    The returned function is jax-traceable: topological interpretation of
    the node DAG over the op registry, with per-node PRNG keys derived by
    ``fold_in`` and functional aux-state threading (the reference mutated
    aux NDArrays in place; here the executor rebinds them).

    ``group2ctx`` maps ``ctx_group`` attr values (attached via
    ``mx.AttrScope(ctx_group=...)``) to Contexts — the group2ctx
    model-parallel mechanism (``graph_executor.cc:279-393`` AssignContext:
    PlaceDevice pass + ``_CrossDeviceCopy`` insertion;
    ``example/model-parallel-lstm/lstm.py:65-68``).  TPU-native form: each
    grouped node's outputs are committed to its group's device *inside*
    the jitted program, so XLA itself plans the graph partition and
    inserts the cross-device transfers — one compiled program spanning the
    devices rather than copy nodes between per-device executors.
    """
    import jax

    nodes = symbol.topo_nodes()
    outputs = symbol._outputs
    aux_names = set(symbol.list_auxiliary_states())

    node_device = {}
    if group2ctx:
        devmap = {g: ctx.jax_device for g, ctx in group2ctx.items()}
        for node in nodes:
            grp = (node.attrs or {}).get("ctx_group")
            if grp is not None and str(grp) in devmap:
                node_device[id(node)] = devmap[str(grp)]

    def fn(arg_vals, aux_vals, key):
        env = {}
        new_aux = dict(aux_vals)
        for ni, node in enumerate(nodes):
            if node.is_variable:
                val = (new_aux[node.name] if node.name in aux_names
                       else arg_vals[node.name])
                dev = node_device.get(id(node))
                if dev is not None:
                    val = jax.device_put(val, dev)
                env[(id(node), 0)] = val
                continue
            ins = [env[(id(inp), idx)] for inp, idx in node.inputs]
            rng = jax.random.fold_in(key, ni) if node.op.needs_rng else None
            outs, naux = node.op.apply(
                ins, node.attrs, OpContext(is_train=is_train, rng=rng))
            dev = node_device.get(id(node))
            if dev is not None:
                outs = [jax.device_put(o, dev) for o in outs]
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
            if node.op.has_aux:
                n_args = len(node.op.get_arg_names(node.attrs))
                for (inp, _), val in zip(node.inputs[n_args:], naux):
                    if inp.is_variable:
                        new_aux[inp.name] = val
        return [env[(id(n), i)] for n, i in outputs], new_aux

    return fn
