"""Symbol → pure-jax-function lowering, shared by the Executor and the
fused parallel train step (single source of truth for op apply / aux
write-back / RNG-key folding semantics), including the recompute
(remat) policy — the reference's ``MXNET_BACKWARD_DO_MIRROR``
(``src/executor/graph_executor.cc:215-273``) redesigned over
``jax.checkpoint``."""
from __future__ import annotations

import time
import weakref

from . import telemetry
from .base import get_env
from .ops.registry import OpContext

__all__ = ["lower_symbol", "lower_symbol_grouped", "resolve_remat"]

# Symbol → {(is_train, remat): lowered fn}.  The lowered function is a pure
# function of the node DAG, so executors bound over the same Symbol share
# one fn — and because jax.jit caches by function identity, they share one
# XLA compilation too.  WeakKey so dropping the Symbol drops the entry.
_LOWER_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


# Ops whose outputs stay resident under the mirror policy: the matmul /
# conv / recurrence results that are expensive to recompute.  Everything
# else (activations, norms, reshapes, elementwise chains) is dropped and
# replayed in backward — the same cheap-op set the reference's mirror
# heuristic targeted (graph_executor.cc:215-273 mirrors Activation/BN/
# Pooling-style nodes; env_var.md:89-94).
_MIRROR_SAVED_OPS = frozenset({
    "Convolution", "Deconvolution", "FullyConnected", "RNN",
    "_contrib_DotProductAttention", "dot", "batch_dot", "Embedding",
    "_contrib_SoftmaxXentHead",
})
_MIRROR_NAME = "tp_mirror_saved"


def resolve_remat(remat):
    """Normalize a remat spec: None defers to the env contract —
    ``TP_BACKWARD_DO_MIRROR`` / ``MXNET_BACKWARD_DO_MIRROR`` (=1 →
    ``'mirror'``, reference env_var.md:89-94) or ``TP_REMAT_SEGMENTS=K``
    (uniform K-segment checkpointing).  Returns ``None``, ``'mirror'``,
    or an int ≥ 1."""
    if remat is not None:
        if remat == "mirror":
            return "mirror"
        # bools are ints in python; remat=True is almost certainly a
        # confusion with the boolean mirror env var — refuse it
        if isinstance(remat, int) and not isinstance(remat, bool) \
                and remat >= 0:
            return remat if remat != 0 else None
        raise ValueError("remat must be None, 'mirror', or an int >= 0 "
                         "(0 = off), got %r" % (remat,))
    if get_env("BACKWARD_DO_MIRROR", False, bool):
        return "mirror"
    segs = get_env("REMAT_SEGMENTS", 0, int)
    return segs if segs > 0 else None


def lower_symbol(symbol, is_train: bool, remat=None):
    """Cached entry over :func:`_lower_symbol_impl`: the per-(symbol,
    mode, remat) lowering is memoized so repeated binds of one Symbol
    (bucketing, shared modules, fwd+bwd over the same graph) skip the
    topo interpretation AND reuse jax.jit's by-identity compile cache.
    Telemetry: ``lowering_cache_{hits,misses}_total``,
    ``lowering_seconds``."""
    remat = resolve_remat(remat) if is_train else None
    ck = (bool(is_train), remat)
    try:
        bucket = _LOWER_CACHE.get(symbol)
    except TypeError:           # unhashable symbol: skip caching
        bucket = None
    if bucket is not None and ck in bucket:
        telemetry.counter("lowering_cache_hits_total").inc()
        return bucket[ck]
    telemetry.counter("lowering_cache_misses_total").inc()
    t0 = time.perf_counter()
    fn = _lower_symbol_impl(symbol, is_train, remat)
    telemetry.histogram("lowering_seconds").observe(
        time.perf_counter() - t0)
    try:
        _LOWER_CACHE.setdefault(symbol, {})[ck] = fn
    except TypeError:
        pass
    return fn


def _lower_symbol_impl(symbol, is_train: bool, remat):
    """Lower a Symbol DAG to ``fn(arg_vals, aux_vals, key) ->
    (outputs, new_aux)``.  ``remat`` arrives pre-resolved (``None``,
    ``'mirror'``, or an int K).

    The returned function is pure and jax-traceable: topological
    interpretation of the node DAG over the op registry, with per-node
    PRNG keys derived by ``fold_in`` and functional aux-state threading
    (the reference mutated aux NDArrays in place; here the executor
    rebinds them).

    ``remat`` (training only) trades recompute FLOPs for activation
    memory, the ``MXNET_BACKWARD_DO_MIRROR`` capability redesigned for
    XLA: ``'mirror'`` wraps the graph in one ``jax.checkpoint`` whose
    policy saves only matmul/conv-family outputs (cheap ops replay in
    backward); an int K splits the topo order into K contiguous
    segments, each checkpointed, so only segment-boundary activations
    survive the forward pass (per-device memory ~ boundaries + one
    segment's internals — the layerwise scheme for deep stacks).
    """
    import jax

    nodes = symbol.topo_nodes()
    outputs = symbol._outputs
    aux_names = set(symbol.list_auxiliary_states())

    mirror = remat == "mirror"

    def fn(arg_vals, aux_vals, key):
        env, new_aux = _interpret(
            enumerate(nodes), {}, arg_vals, aux_vals, key,
            is_train=is_train, aux_names=aux_names, mirror=mirror)
        return [env[(id(n), i)] for n, i in outputs], new_aux

    if remat is None:
        return fn
    if mirror:
        policy = jax.checkpoint_policies.save_only_these_names(
            _MIRROR_NAME)
        return jax.checkpoint(fn, policy=policy)
    return _lower_segmented(nodes, outputs, aux_names, int(remat))


def _interpret(node_list, env, arg_vals, aux_vals, key, *, is_train,
               aux_names, mirror=False):
    """THE interpretation loop (single source of truth for op apply /
    RNG fold-in / aux write-back): run ``(ni, node)`` pairs over a
    pre-seeded ``env``, returning ``(env, new_aux)``.  ``mirror`` tags
    matmul/conv-family outputs for the checkpoint save policy."""
    import jax

    if mirror:
        from jax.ad_checkpoint import checkpoint_name
    new_aux = dict(aux_vals)
    for ni, node in node_list:
        if node.is_variable:
            env[(id(node), 0)] = (new_aux[node.name]
                                  if node.name in aux_names
                                  else arg_vals[node.name])
            continue
        ins = [env[(id(inp), idx)] for inp, idx in node.inputs]
        rng = jax.random.fold_in(key, ni) if node.op.needs_rng else None
        outs, naux = node.op.apply(
            ins, node.attrs, OpContext(is_train=is_train, rng=rng))
        if mirror and node.op.name in _MIRROR_SAVED_OPS:
            outs = [checkpoint_name(o, _MIRROR_NAME) for o in outs]
        for i, o in enumerate(outs):
            env[(id(node), i)] = o
        if node.op.has_aux:
            n_args = len(node.op.get_arg_names(node.attrs))
            for (inp, _), val in zip(node.inputs[n_args:], naux):
                if inp.is_variable:
                    new_aux[inp.name] = val
    return env, new_aux


def _lower_segmented(nodes, outputs, aux_names, nseg):
    """K-segment checkpointed lowering: contiguous topo chunks, each
    under ``jax.checkpoint`` so only boundary values are saved."""
    import jax

    compute = [(ni, n) for ni, n in enumerate(nodes) if not n.is_variable]
    nseg = max(1, min(nseg, len(compute)))
    per = -(-len(compute) // nseg)  # ceil
    chunks = [compute[i:i + per] for i in range(0, len(compute), per)]

    var_by_id = {id(n): n for n in nodes if n.is_variable}
    out_entries = [(id(n), i) for n, i in outputs]

    segs = []
    for chunk in chunks:
        ids = {id(n) for _, n in chunk}
        ext, seen = [], set()
        for _, node in chunk:
            for inp, idx in node.inputs:
                k = (id(inp), idx)
                if id(inp) not in ids and k not in seen:
                    seen.add(k)
                    ext.append(k)
        segs.append({"nodes": chunk, "ids": ids, "ext_keys": ext})
    cross = set(out_entries)
    for seg in segs:
        cross.update(seg["ext_keys"])
    for seg in segs:
        seg["out_keys"] = sorted(k for k in cross if k[0] in seg["ids"])

    def make_seg_fn(seg):
        seg_nodes = seg["nodes"]
        ext_keys = tuple(seg["ext_keys"])
        out_keys = tuple(seg["out_keys"])

        def seg_fn(ext_vals, aux_vals, key):
            # boundary values pre-seed env; chunks hold no variable
            # nodes (those resolve at the driver), so arg_vals is empty
            env, new_aux = _interpret(
                seg_nodes, dict(zip(ext_keys, ext_vals)), {}, aux_vals,
                key, is_train=True, aux_names=aux_names)
            upd = {k: v for k, v in new_aux.items()
                   if v is not aux_vals.get(k)}
            return [env[k] for k in out_keys], upd

        return jax.checkpoint(seg_fn)

    for seg in segs:
        seg["fn"] = make_seg_fn(seg)

    def fn(arg_vals, aux_vals, key):
        new_aux = dict(aux_vals)
        env = {}

        def resolve(k):
            var = var_by_id.get(k[0])
            if var is not None:
                return (aux_vals[var.name] if var.name in aux_names
                        else arg_vals[var.name])
            return env[k]

        for seg in segs:
            ext_vals = [resolve(k) for k in seg["ext_keys"]]
            out_vals, upd = seg["fn"](ext_vals, aux_vals, key)
            for k, v in zip(seg["out_keys"], out_vals):
                env[k] = v
            new_aux.update(upd)
        return [resolve(k) for k in out_entries], new_aux

    return fn


def lower_symbol_grouped(symbol, is_train: bool, group2ctx, default_device):
    """group2ctx model-parallel lowering (``graph_executor.cc:279-393``
    AssignContext: PlaceDevice pass + ``_CrossDeviceCopy`` insertion;
    ``example/model-parallel-lstm/lstm.py:65-68``).

    TPU-native form of the reference's design: the topo-ordered node list
    is partitioned into contiguous same-device *segments*; each segment is
    compiled as its own jitted subprogram on its group's device, and the
    eager driver inserts explicit ``jax.device_put`` transfers at segment
    boundaries (the ``_CrossDeviceCopy`` nodes).  The driver itself is NOT
    jittable — jax.jit refuses arguments committed to different devices —
    but it IS differentiable: ``jax.vjp`` traces through the per-segment
    jits and the transfers, moving cotangents back across the boundary.

    Returns ``fn(arg_vals, aux_vals, key) -> (outputs, new_aux)`` to be
    invoked eagerly (do not wrap in jax.jit).
    """
    import jax

    telemetry.counter("lowering_grouped_total").inc()

    nodes = symbol.topo_nodes()
    outputs = symbol._outputs
    aux_names = set(symbol.list_auxiliary_states())
    var_by_id = {id(n): n for n in nodes if n.is_variable}

    devmap = {g: ctx.jax_device for g, ctx in group2ctx.items()}

    def node_dev(node):
        grp = (node.attrs or {}).get("ctx_group")
        if grp is not None and str(grp) in devmap:
            return devmap[str(grp)]
        return default_device

    # ---- partition into per-device *stages*, not contiguous topo runs: a
    # node's stage only advances past its producers when the edge crosses
    # devices, so all same-device nodes that can run together share ONE
    # jitted segment (the PlaceDevice partition) even when the topo order
    # interleaves groups (e.g. a time-unrolled model-parallel LSTM)
    stage = {}
    for node in nodes:
        if node.is_variable:
            continue
        d = node_dev(node)
        st = 0
        for inp, _ in node.inputs:
            if inp.is_variable:
                continue
            st = max(st, stage[id(inp)] if node_dev(inp) == d
                     else stage[id(inp)] + 1)
        stage[id(node)] = st

    segs = []  # each: {dev, nodes: [(global_idx, node)]} in stage order
    key2seg = {}
    for ni, node in enumerate(nodes):
        if node.is_variable:
            continue
        k = (stage[id(node)], node_dev(node))
        seg = key2seg.get(k)
        if seg is None:
            seg = {"dev": node_dev(node), "stage": stage[id(node)],
                   "nodes": []}
            key2seg[k] = seg
            segs.append(seg)
        seg["nodes"].append((ni, node))
    segs.sort(key=lambda s: s["stage"])  # stable within a stage

    out_entries = [(id(n), i) for n, i in outputs]
    for seg in segs:
        seg["ids"] = {id(node) for _, node in seg["nodes"]}
        ext, seen = [], set()
        for _, node in seg["nodes"]:
            for inp, idx in node.inputs:
                k = (id(inp), idx)
                if id(inp) not in seg["ids"] and k not in seen:
                    seen.add(k)
                    ext.append(k)
        seg["ext_keys"] = ext

    # a segment exports only what crosses its boundary — entries consumed
    # by OTHER segments or in the final outputs; same-segment intermediates
    # stay inside the jit so XLA can fuse/rematerialize them
    cross = set(out_entries)
    for seg in segs:
        cross.update(seg["ext_keys"])
    for seg in segs:
        seg["out_keys"] = sorted(k for k in cross if k[0] in seg["ids"])

    def make_seg_fn(seg):
        seg_nodes = seg["nodes"]
        ext_keys = tuple(seg["ext_keys"])
        out_keys = tuple(seg["out_keys"])

        def seg_fn(ext_vals, key):
            env = dict(zip(ext_keys, ext_vals))
            upd = {}
            for ni, node in seg_nodes:
                ins = [env[(id(inp), idx)] for inp, idx in node.inputs]
                rng = (jax.random.fold_in(key, ni)
                       if node.op.needs_rng else None)
                outs, naux = node.op.apply(
                    ins, node.attrs, OpContext(is_train=is_train, rng=rng))
                for i, o in enumerate(outs):
                    env[(id(node), i)] = o
                if node.op.has_aux:
                    n_args = len(node.op.get_arg_names(node.attrs))
                    for (inp, _), val in zip(node.inputs[n_args:], naux):
                        if inp.is_variable:
                            upd[inp.name] = val
            return [env[k] for k in out_keys], upd

        return jax.jit(seg_fn)

    for seg in segs:
        seg["fn"] = make_seg_fn(seg)

    def fn(arg_vals, aux_vals, key):
        aux_state = dict(aux_vals)
        env = {}

        def resolve(k):
            var = var_by_id.get(k[0])
            if var is not None:
                return (aux_state[var.name] if var.name in aux_names
                        else arg_vals[var.name])
            return env[k]

        for seg in segs:
            dev = seg["dev"]
            ext_vals = [jax.device_put(resolve(k), dev)
                        for k in seg["ext_keys"]]
            out_vals, upd = seg["fn"](ext_vals, jax.device_put(key, dev))
            for k, v in zip(seg["out_keys"], out_vals):
                env[k] = v
            aux_state.update(upd)
        return [resolve(k) for k in out_entries], aux_state

    fn._segments = segs  # introspection for tests/debugging
    return fn
