"""Symbol → pure-jax-function lowering, shared by the Executor and the
fused parallel train step (single source of truth for op apply / aux
write-back / RNG-key folding semantics)."""
from __future__ import annotations

from .ops.registry import OpContext

__all__ = ["lower_symbol"]


def lower_symbol(symbol, is_train: bool):
    """Lower a Symbol DAG to ``fn(arg_vals, aux_vals, key) ->
    (outputs, new_aux)``.

    The returned function is jax-traceable: topological interpretation of
    the node DAG over the op registry, with per-node PRNG keys derived by
    ``fold_in`` and functional aux-state threading (the reference mutated
    aux NDArrays in place; here the executor rebinds them).
    """
    nodes = symbol.topo_nodes()
    outputs = symbol._outputs
    aux_names = set(symbol.list_auxiliary_states())

    def fn(arg_vals, aux_vals, key):
        import jax

        env = {}
        new_aux = dict(aux_vals)
        for ni, node in enumerate(nodes):
            if node.is_variable:
                env[(id(node), 0)] = (new_aux[node.name]
                                      if node.name in aux_names
                                      else arg_vals[node.name])
                continue
            ins = [env[(id(inp), idx)] for inp, idx in node.inputs]
            rng = jax.random.fold_in(key, ni) if node.op.needs_rng else None
            outs, naux = node.op.apply(
                ins, node.attrs, OpContext(is_train=is_train, rng=rng))
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
            if node.op.has_aux:
                n_args = len(node.op.get_arg_names(node.attrs))
                for (inp, _), val in zip(node.inputs[n_args:], naux):
                    if inp.is_variable:
                        new_aux[inp.name] = val
        return [env[(id(n), i)] for n, i in outputs], new_aux

    return fn
