"""Legacy multi-device executor helpers (``python/mxnet/executor_manager.py``).

``DataParallelExecutorManager`` predates Module in the reference; kept for
API parity.  Internally it drives the same
:class:`~incubator_mxnet_tpu.module.executor_group.DataParallelExecutorGroup`
the Module stack uses.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .module.executor_group import (DataParallelExecutorGroup,
                                    _split_input_slice)

__all__ = ["_split_input_slice", "_check_arguments",
           "DataParallelExecutorManager"]


def _check_arguments(symbol):
    """Reject duplicate argument/aux names
    (reference ``executor_manager.py:68``)."""
    arg_names = symbol.list_arguments()
    if len(set(arg_names)) != len(arg_names):
        seen = set()
        for name in arg_names:
            if name in seen:
                raise MXNetError(
                    "Find duplicated argument name \"%s\"; please make the "
                    "weight name non-duplicated, arguments are %s"
                    % (name, str(arg_names)))
            seen.add(name)
    aux_names = symbol.list_auxiliary_states()
    if len(set(aux_names)) != len(aux_names):
        raise MXNetError("Duplicated auxiliary state names")


class DataParallelExecutorManager:
    """Helper managing per-device executors for data parallelism
    (reference ``executor_manager.py:295``)."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        if logger is None:
            logger = logging
        self.symbol = symbol
        self.sym_gen = sym_gen
        self.ctx = ctx
        self.logger = logger
        num_device = len(ctx)
        logger.info("Start training with %s", str(ctx))

        if work_load_list is None:
            work_load_list = [1] * num_device
        assert isinstance(work_load_list, list) and \
            len(work_load_list) == num_device
        self.work_load_list = work_load_list

        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        input_names = [d[0] for d in train_data.provide_data] + \
            [l[0] for l in (train_data.provide_label or [])]
        self.param_names = param_names or \
            [n for n in self.arg_names if n not in input_names]

        self.slices = _split_input_slice(train_data.batch_size,
                                         work_load_list)
        self.execgrp = DataParallelExecutorGroup(
            symbol, ctx, work_load_list, train_data.provide_data,
            train_data.provide_label, self.param_names, for_training=True,
            logger=logger)
        self.execgrp_bucket = {}
        if sym_gen is not None and \
                getattr(train_data, "default_bucket_key", None) is not None:
            self.execgrp_bucket[train_data.default_bucket_key] = \
                self.execgrp
        self.curr_execgrp = self.execgrp

    def install_monitor(self, monitor):
        if self.sym_gen is not None:
            raise MXNetError(
                "Monitoring is not implemented for bucketing")
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Copy device params back into the given host dicts."""
        self.curr_execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        return self.curr_execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.curr_execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.curr_execgrp.aux_arrays

    def load_data_batch(self, data_batch):
        if self.sym_gen is not None and \
                data_batch.bucket_key not in (None,):
            key = data_batch.bucket_key
            if key not in self.execgrp_bucket:
                symbol = self.sym_gen(key)
                self.execgrp_bucket[key] = DataParallelExecutorGroup(
                    symbol, self.ctx, self.work_load_list,
                    data_batch.provide_data, data_batch.provide_label,
                    self.param_names, for_training=True,
                    shared_group=self.execgrp, logger=self.logger)
            self.curr_execgrp = self.execgrp_bucket[key]
        self._pending_batch = data_batch

    def forward(self, is_train=False):
        self.curr_execgrp.forward(self._pending_batch, is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels):
        self.curr_execgrp.update_metric(metric, labels)
