"""Custom python operators — ``mx.operator.CustomOp`` / ``CustomOpProp``.

Reference analog: ``python/mxnet/operator.py:413-676`` + the C++ side
``src/operator/custom/custom-inl.h`` (which ran python callbacks on a
dedicated worker thread with a task queue).

TPU-native redesign: the host callback rides ``jax.pure_callback`` — XLA
calls back into python from inside the compiled program, which is the XLA
equivalent of the reference's callback worker thread.  Gradients are a
``jax.custom_vjp`` whose backward is a second host callback into
``CustomOp.backward``; that keeps custom ops usable under ``autograd``,
``Module`` and even inside a jitted/sharded step.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]


class CustomOp(object):
    """Base class for custom python operators
    (reference ``operator.py:413``)."""

    def __init__(self):
        pass

    def forward(self, is_train, req, in_data, out_data, aux):
        """Compute outputs from ``in_data`` (numpy arrays); write results
        with ``self.assign(out_data[i], req[i], value)``."""
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Compute input gradients; write with
        ``self.assign(in_grad[i], req[i], value)``."""
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the OpReqType
        (reference ``operator.py:450``)."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] += src
        else:
            raise MXNetError("invalid req %s" % req)


class CustomOpProp(object):
    """Operator properties: names/shapes/types
    (reference ``operator.py:459``)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        """Default: all inputs and outputs take the first input's shape."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), \
            [in_shape[0]] * len(self.list_auxiliary_states())

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


_custom_registry: Dict[str, type] = {}


def register(reg_name):
    """Decorator registering a ``CustomOpProp`` subclass under
    ``op_type=reg_name`` (reference ``operator.py:593``); usable as
    ``mx.nd.Custom(..., op_type=reg_name)`` / ``mx.sym.Custom(...)``."""

    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("can only register subclass of CustomOpProp")
        _custom_registry[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered() -> List[str]:
    return sorted(_custom_registry)


def _make_prop(attrs: Dict[str, Any]) -> CustomOpProp:
    op_type = attrs.get("op_type")
    if op_type is None:
        raise MXNetError("Custom op requires an op_type attribute")
    if op_type not in _custom_registry:
        raise MXNetError("custom op type '%s' is not registered; known: %s"
                         % (op_type, get_all_registered()))
    kwargs = {k: v for k, v in attrs.items() if k != "op_type"}
    return _custom_registry[op_type](**kwargs)


def _custom_arg_names(attrs):
    return list(_make_prop(attrs).list_arguments())


def _custom_num_outputs(attrs):
    return len(_make_prop(attrs).list_outputs())


def _custom_infer_shape(in_shapes, attrs):
    prop = _make_prop(attrs)
    n_args = len(prop.list_arguments())
    if any(s is None for s in in_shapes[:n_args]):
        # reference semantics (CustomOpProp.InferShape gets whatever is
        # known and BACK-FILLS the rest — how example/dec's DECLoss
        # deduces the `mu` shape from `data` alone): attempt the prop's
        # rule with the partial shapes; a prop that needs more raises,
        # and shape inference proceeds with everything unknown.
        try:
            ins, outs, auxs = prop.infer_shape(
                [list(s) if s is not None else None
                 for s in in_shapes[:n_args]])
        except (TypeError, IndexError, KeyError) as e:
            # only the failure modes of a prop poking into still-None
            # shapes; anything else (a genuine bug in the prop) must
            # surface, not dissolve into "shape unknown"
            import logging

            logging.getLogger(__name__).debug(
                "partial infer_shape for %s deferred: %s",
                attrs.get("op_type"), e)
            return in_shapes, [None] * len(prop.list_outputs()), []
        return [tuple(s) if s is not None else None for s in ins], \
            [tuple(s) if s is not None else None for s in outs], \
            [tuple(s) if s is not None else None for s in auxs]
    ins, outs, auxs = prop.infer_shape([list(s)
                                        for s in in_shapes[:n_args]])
    return [tuple(s) for s in ins], [tuple(s) for s in outs], \
        [tuple(s) for s in auxs]


def _install_custom_op():
    """Register the single ``Custom`` operator that dispatches on
    ``op_type`` (the reference did the same through the C custom-op
    registry, ``src/c_api/c_api.cc`` MXCustomOpRegister)."""
    import jax

    from .ops.registry import register as op_register

    @op_register("Custom", arg_names=_custom_arg_names,
                 num_outputs=_custom_num_outputs,
                 infer_shape=_custom_infer_shape)
    def _custom(ins, attrs, ctx):
        prop = _make_prop(attrs)
        if prop.list_auxiliary_states():
            raise MXNetError(
                "Custom ops with auxiliary states are not supported on "
                "the TPU backend yet (op_type=%s); keep mutable state on "
                "the CustomOp instance instead" % attrs.get("op_type"))
        in_shapes = [tuple(x.shape) for x in ins]
        in_dtypes = [x.dtype for x in ins]
        _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
        _, out_types, _ = prop.infer_type(list(in_dtypes))
        out_struct = [jax.ShapeDtypeStruct(tuple(s), t)
                      for s, t in zip(out_shapes, out_types)]
        in_struct = [jax.ShapeDtypeStruct(s, t)
                     for s, t in zip(in_shapes, in_dtypes)]
        n_out = len(out_struct)
        is_train = bool(ctx.is_train)
        # one operator instance per bound graph, shared by forward and
        # backward so state stashed on self in forward is visible in
        # backward (the reference kept one Operator per executor too)
        op_holder = []

        def _operator():
            if not op_holder:
                op_holder.append(
                    prop.create_operator(None, in_shapes, in_dtypes))
            return op_holder[0]

        def host_forward(*arrays):
            op = _operator()
            in_data = [np.asarray(a) for a in arrays]
            out_data = [np.zeros(s.shape, s.dtype) for s in out_struct]
            op.forward(is_train=is_train, req=["write"] * n_out,
                       in_data=in_data, out_data=out_data, aux=[])
            return tuple(out_data)

        def host_backward(*arrays):
            k = len(ins)
            in_data = [np.asarray(a) for a in arrays[:k]]
            out_data = [np.asarray(a) for a in arrays[k:k + n_out]]
            out_grad = [np.asarray(a) for a in arrays[k + n_out:]]
            op = _operator()
            in_grad = [np.zeros(s, d) for s, d in zip(in_shapes,
                                                      in_dtypes)]
            op.backward(req=["write"] * k, out_grad=out_grad,
                        in_data=in_data, out_data=out_data,
                        in_grad=in_grad, aux=[])
            return tuple(in_grad)

        @jax.custom_vjp
        def call(*xs):
            outs = jax.pure_callback(host_forward, tuple(out_struct), *xs)
            return tuple(outs)

        def call_fwd(*xs):
            outs = jax.pure_callback(host_forward, tuple(out_struct), *xs)
            return tuple(outs), (xs, tuple(outs))

        def call_bwd(res, gs):
            xs, outs = res
            grads = jax.pure_callback(host_backward, tuple(in_struct),
                                      *(xs + outs + tuple(gs)))
            return tuple(grads)

        call.defvjp(call_fwd, call_bwd)
        outs = call(*ins)
        if n_out == 1:
            return outs[0]
        return tuple(outs)


_install_custom_op()

# refresh the generated namespaces — this module registers "Custom" after
# mx.nd / mx.sym built their op tables at import time
from .ndarray import _install_ops as _refresh_nd  # noqa: E402

_refresh_nd()
try:
    from .symbol import _install as _refresh_sym  # noqa: E402

    _refresh_sym()
except ImportError:  # symbol layer not present yet during early bootstrap
    pass


class PythonOp(object):
    """Deprecated v0.8-style base (reference ``operator.py:36``); prefer
    CustomOp.  Kept for API parity — ``get_symbol`` wires the op into a
    graph via an auto-registered CustomOpProp adapter."""

    _op_counter = [0]

    def __init__(self, need_top_grad=True):
        self.info_ = None
        self.need_top_grad_ = need_top_grad

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def need_top_grad(self):
        return self.need_top_grad_

    def get_symbol(self, *args, **kwargs):
        raise NotImplementedError


class NumpyOp(PythonOp):
    """Numpy-backed legacy op (reference ``operator.py:143``)."""

    def get_symbol(self, *args, **kwargs):
        from . import symbol as sym_mod

        legacy = self

        class _Adapter(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=legacy.need_top_grad())

            def list_arguments(self):
                return legacy.list_arguments()

            def list_outputs(self):
                return legacy.list_outputs()

            def infer_shape(self, in_shape):
                ins, outs = legacy.infer_shape(in_shape)
                return ins, outs, []

            def create_operator(self, ctx, in_shapes, in_dtypes):
                class _Op(CustomOp):
                    def forward(self, is_train, req, in_data, out_data,
                                aux):
                        legacy.forward(in_data=in_data, out_data=out_data)

                    def backward(self, req, out_grad, in_data, out_data,
                                 in_grad, aux):
                        legacy.backward(out_grad=out_grad,
                                        in_data=in_data,
                                        out_data=out_data,
                                        in_grad=in_grad)

                return _Op()

        PythonOp._op_counter[0] += 1
        name = "_numpy_op_%d" % PythonOp._op_counter[0]
        register(name)(_Adapter)
        kwargs["op_type"] = name
        return sym_mod.Custom(*args, **kwargs)


NDArrayOp = NumpyOp  # the reference NDArrayOp differs only in buffer type
