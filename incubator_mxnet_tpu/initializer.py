"""Weight initializers (``python/mxnet/initializer.py``): registry +
Uniform/Normal/Xavier/MSRAPrelu/Orthogonal/Bilinear/One/Zero/Constant/
LSTMBias/Mixed/Load, with the name-pattern dispatch the reference uses
(``_bias`` → zero, ``_gamma`` → one, …)."""
from __future__ import annotations

import json
import re
from typing import Any, Dict, Optional

import numpy as np

from .base import Registry
from .ndarray.ndarray import NDArray

__all__ = ["Initializer", "Uniform", "Normal", "Xavier", "MSRAPrelu",
           "Orthogonal", "Bilinear", "One", "Zero", "Constant", "LSTMBias",
           "Mixed", "Load", "InitDesc", "register", "create", "init"]

_REG = Registry("initializer")
register = _REG.register


class InitDesc(str):
    """Parameter name + attrs descriptor (reference ``InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr: NDArray) -> None:
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init_attr = desc.attrs.get("__init__")
        if init_attr:
            create(init_attr)._init_weight(desc, arr)
            return
        name = str(desc).lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # hooks
    def _init_bilinear(self, desc, arr):
        Bilinear()._init_weight(desc, arr)

    def _init_bias(self, desc, arr):
        arr[:] = 0.0

    def _init_gamma(self, desc, arr):
        arr[:] = 1.0

    def _init_beta(self, desc, arr):
        arr[:] = 0.0

    def _init_zero(self, desc, arr):
        arr[:] = 0.0

    def _init_one(self, desc, arr):
        arr[:] = 1.0

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def __repr__(self):
        return "%s(%s)" % (self.__class__.__name__, self._kwargs)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape
                                   ).astype(np.float32)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape
                                  ).astype(np.float32)


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference ``initializer.py`` Xavier: rnd_type,
    factor_type, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def weight_scale(self, shape):
        """The per-shape scale of this initializer's distribution —
        shared with the on-chip init plan (``parallel/fused.py``) so
        host and device paths cannot drift."""
        hw_scale = 1.0
        if len(shape) < 2:
            fan_in = fan_out = shape[0] if shape else 1
        else:
            if len(shape) > 2:
                hw_scale = float(np.prod(shape[2:]))
            fan_in = shape[1] * hw_scale
            fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        return float(np.sqrt(self.magnitude / factor))

    def _init_weight(self, desc, arr):
        shape = arr.shape
        scale = self.weight_scale(shape)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape
                                       ).astype(np.float32)
        else:
            arr[:] = np.random.normal(0, scale, shape).astype(np.float32)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        Initializer.__init__(self, factor_type=factor_type, slope=slope)
        self.rnd_type = "gaussian"
        self.factor_type = factor_type
        self.magnitude = magnitude


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(np.float32)


@register
class Bilinear(Initializer):
    def _init_weight(self, desc, arr):
        weight = np.zeros(arr.shape, dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_bias = _init_weight
    _init_default = _init_weight


class Mixed:
    """Pattern-dispatch initializer (reference ``Mixed``)."""

    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, desc, arr):
        for prog, i in self.map:
            if prog.match(str(desc)):
                i(desc, arr)
                return
        raise ValueError("no initializer pattern matches %s" % desc)


class Load:
    """Init from a saved param dict, falling back to default_init
    (reference ``Load``)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load

            param = nd_load(param)
        self.param = {k.split(":", 1)[-1]: v for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        name = str(name)
        if name in self.param:
            arr[:] = self.param[name].asnumpy()
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise ValueError("no init for %s" % name)


def create(spec) -> Initializer:
    if isinstance(spec, Initializer):
        return spec
    if isinstance(spec, str):
        if spec.startswith("["):
            name, kwargs = json.loads(spec)
            return _REG.get(name)(**kwargs)
        return _REG.get(spec)()
    raise ValueError("cannot create initializer from %r" % spec)


class _InitNamespace:
    """``mx.init.Xavier()`` style access."""

    Uniform = Uniform
    Normal = Normal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Orthogonal = Orthogonal
    Bilinear = Bilinear
    One = One
    Zero = Zero
    Constant = Constant
    LSTMBias = LSTMBias
    Mixed = Mixed
    Load = Load
    Initializer = Initializer
    InitDesc = InitDesc


init = _InitNamespace
