"""Distributed request/step tracing — the flight recorder.

PR 2's telemetry registry answers *how much* (counters, histograms,
Chrome ``"C"`` samples); this module answers *where an individual p99
went*.  It is a Dapper-style span layer (Sigelman et al., 2010):
every traced request owns a ``trace_id``, every phase a
``(span_id, parent_id)`` pair with monotonic-clock timestamps, and the
context rides every hop the stack already owns — router admission →
length-prefixed RPC framing → replica engine → chunked prefill /
speculative verify ticks — so ``tools/trace_query.py`` can mine the
span trees for Mystery-Machine-style critical-path attribution
(Chow et al., OSDI'14).

Design contract (mirrors ``telemetry.py``):

* **Disabled mode is zero-allocation.**  The module gate is one global
  (``_REC``); every entry point early-returns ``None`` when it is
  unset, and no hot-path signature takes ``**kwargs`` (a kwargs call
  allocates a dict even when the callee ignores it).  Call sites keep
  the contract by guarding ``if ctx is not None:`` so span bookkeeping
  never executes when tracing is off.
* **Tail-based sampling.**  The keep/drop decision happens when a
  trace *finishes*, so traces that shed, error, or bust their deadline
  class are always kept (``flag()``), and only the boring rest is
  down-sampled.  Healthy traces are kept deterministically by hashing
  the trace id against ``TP_TRACING_SAMPLE`` — a distributed trace's
  fragments reach the same verdict on every process without a
  coordination round-trip.
* **Bounded memory.**  Finished-and-kept traces land in a
  ``deque(maxlen=TP_TRACING_RING)`` flight-recorder ring; live traces
  are capped too (oldest evicted) so leaked contexts cannot grow
  without bound.
* **Two exposition formats**, like telemetry: a queryable JSONL (one
  trace per line, consumed by ``tools/trace_query.py``) and Chrome
  async ``"b"``/``"e"`` events keyed by trace id merged into the
  existing profiler trace next to the ``"C"`` counters.

Wire format: ``SpanContext.to_wire()`` is a plain ``(trace_id,
span_id)`` int tuple — it pickles inside the existing ps.py framing
with no schema change.  ``from_wire`` on the receiving side either
joins the local trace (in-process replica) or *adopts* the id as a
remote-owned fragment that ``finish_remote`` finalizes after the
reply is sent.

Env knobs (``docs/env_var.md``): ``TP_TRACING=1`` enables at import;
``TP_TRACING_SAMPLE`` (default 0.05) keep-fraction for unflagged
traces; ``TP_TRACING_RING`` (default 512) ring capacity;
``TP_TRACING_PATH`` (default ``traces.jsonl``) flush target.
"""
from __future__ import annotations

import atexit
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import profiler
from .base import get_env

__all__ = ["SpanContext", "enabled", "enable", "disable", "start_trace",
           "end_trace", "record", "flag", "from_wire", "finish_remote",
           "set_train_context", "train_context", "flush", "drain",
           "stats"]

# deterministic hash → [0, 1): Knuth multiplicative on the low 32 bits,
# so every process holding a fragment of the same trace samples it the
# same way
_HASH_MUL = 2654435761
_HASH_MOD = 1 << 32


def _sample_key(trace_id: int) -> float:
    return ((trace_id * _HASH_MUL) % _HASH_MOD) / _HASH_MOD


class SpanContext:
    """Propagated handle: the trace plus the span new children parent
    to.  Immutable by convention; cheap enough to mint per hop."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_wire(self) -> Tuple[int, int]:
        """Plain-tuple form that pickles inside the RPC framing."""
        return (self.trace_id, self.span_id)

    def __repr__(self):
        return "SpanContext(%x, %d)" % (self.trace_id, self.span_id)


class _Trace:
    __slots__ = ("trace_id", "name", "t0", "t1", "root_id", "spans",
                 "flags", "remote", "attrs")

    def __init__(self, trace_id, name, t0, root_id, remote, attrs):
        self.trace_id = trace_id
        self.name = name
        self.t0 = t0
        self.t1 = None
        self.root_id = root_id
        # (span_id, parent_id, name, t0, t1, attrs) tuples
        self.spans: List[tuple] = []
        self.flags: List[str] = []
        self.remote = remote
        self.attrs = attrs

    def to_dict(self) -> Dict[str, Any]:
        d = {"trace_id": "%016x" % self.trace_id, "name": self.name,
             "t0": self.t0, "t1": self.t1, "flags": list(self.flags),
             "remote": self.remote,
             "spans": [{"span_id": s[0], "parent_id": s[1],
                        "name": s[2], "t0": s[3], "t1": s[4],
                        "attrs": s[5]} for s in self.spans]}
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _Recorder:
    """The flight recorder: live traces + the kept-trace ring."""

    # live-trace cap — leaked contexts (a caller that never reaches
    # end_trace) must not grow without bound; oldest-first eviction
    # matches the ring's flight-recorder semantics
    MAX_ACTIVE = 4096

    def __init__(self, path: str, sample: float, ring: int):
        self.path = path
        self.sample = float(sample)
        self._lock = threading.Lock()
        self._active: Dict[int, _Trace] = {}
        self.ring: deque = deque(maxlen=max(1, int(ring)))
        self._next_id = 1
        # seeded off the monotonic epoch so concurrent processes mint
        # disjoint trace ids without coordination
        self._id_base = (int(time.monotonic_ns()) * _HASH_MUL) \
            & ((1 << 62) - 1)
        # one-time clock bridge: spans carry time.monotonic() (the
        # repo-wide deadline clock); the Chrome trace runs on the
        # profiler's perf_counter epoch
        self._mono_off = time.perf_counter() - time.monotonic()
        self.kept = 0
        self.dropped = 0

    # ------------------------------------------------------------- ids
    def _new_id(self) -> int:
        # caller holds self._lock
        i = self._next_id
        self._next_id += 1
        return i

    # ---------------------------------------------------------- traces
    def start(self, name: str, attrs) -> SpanContext:
        t0 = time.monotonic()
        with self._lock:
            sid = self._new_id()
            tid = (self._id_base + sid) & ((1 << 62) - 1)
            self._evict_locked()
            self._active[tid] = _Trace(tid, name, t0, sid, False, attrs)
        return SpanContext(tid, sid)

    def adopt(self, tid: int, sid: int) -> SpanContext:
        """Register a remote-minted trace id as a local fragment."""
        with self._lock:
            if tid not in self._active:
                self._evict_locked()
                self._active[tid] = _Trace(
                    tid, "remote", time.monotonic(), sid, True, None)
        return SpanContext(tid, sid)

    def _evict_locked(self):
        while len(self._active) >= self.MAX_ACTIVE:
            old = next(iter(self._active))
            del self._active[old]
            self.dropped += 1

    def record(self, ctx, name, t0, t1, attrs, parent) -> Optional[int]:
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None:
                return None  # trace already finalized — late span
            sid = self._new_id()
            tr.spans.append((sid, parent if parent is not None
                             else ctx.span_id, name, t0, t1, attrs))
        return sid

    def flag(self, ctx, reason: str) -> None:
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is not None and reason not in tr.flags:
                tr.flags.append(reason)

    def finish(self, ctx, remote_only: bool) -> None:
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None or (remote_only and not tr.remote):
                return
            del self._active[ctx.trace_id]
            tr.t1 = time.monotonic()
            # tail decision: flagged traces always survive; the rest by
            # the deterministic per-trace hash
            if tr.flags or _sample_key(tr.trace_id) < self.sample:
                self.ring.append(tr)
                self.kept += 1
            else:
                self.dropped += 1

    # ------------------------------------------------------------ drain
    def drain(self) -> List[Dict[str, Any]]:
        out = []
        with self._lock:
            while self.ring:
                out.append(self.ring.popleft())
        return [t.to_dict() for t in out]

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        with self._lock:
            traces = list(self.ring)
            self.ring.clear()
        if not traces:
            return None
        path = path or self.path
        with open(path, "a") as f:
            for tr in traces:
                f.write(json.dumps(tr.to_dict()) + "\n")
        # mirror into the Chrome trace as async events keyed by the
        # trace id — each trace renders as one async track next to the
        # telemetry "C" counters
        off = self._mono_off
        for tr in traces:
            aid = "%016x" % tr.trace_id
            profiler.record_async(tr.name, aid, tr.t0 + off,
                                  (tr.t1 if tr.t1 is not None
                                   else tr.t0) + off,
                                  cat="trace",
                                  args={"flags": tr.flags,
                                        "span_id": tr.root_id})
            for sid, pid, name, t0, t1, attrs in tr.spans:
                args = {"span_id": sid, "parent_id": pid}
                if attrs:
                    args.update(attrs)
                profiler.record_async(name, aid, t0 + off, t1 + off,
                                      cat="trace", args=args)
        return path

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"active": len(self._active), "ring": len(self.ring),
                    "kept": self.kept, "dropped": self.dropped,
                    "sample": self.sample,
                    "ring_capacity": self.ring.maxlen}


# ---------------------------------------------------------------------------
# module state — one process-wide recorder, exactly like telemetry._REG
# ---------------------------------------------------------------------------

_REC: Optional[_Recorder] = None
_state_lock = threading.Lock()
_atexit_registered = False
# the train loop's current step context (fit is single-threaded; the
# helpers that record against it — fences, PS RPCs, checkpoint writes —
# read it without coordination)
_train_ctx: Optional[SpanContext] = None


def enabled() -> bool:
    return _REC is not None


def enable(path: Optional[str] = None, sample: Optional[float] = None,
           ring: Optional[int] = None) -> None:
    """Turn the recorder on (idempotent; reconfigures if repeated)."""
    global _REC, _atexit_registered
    with _state_lock:
        _REC = _Recorder(
            path if path is not None
            else get_env("TRACING_PATH", "traces.jsonl"),
            sample if sample is not None
            else get_env("TRACING_SAMPLE", 0.05, float),
            ring if ring is not None
            else get_env("TRACING_RING", 512, int))
        if not _atexit_registered:
            atexit.register(_at_exit)
            _atexit_registered = True


def disable() -> None:
    """Flush and turn the recorder off (tests; symmetric with
    ``telemetry.disable``)."""
    global _REC, _train_ctx
    with _state_lock:
        rec = _REC
        _REC = None
        _train_ctx = None
    if rec is not None:
        try:
            rec.flush()
        except OSError:
            pass


def _at_exit() -> None:
    rec = _REC
    if rec is not None:
        try:
            rec.flush()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# hot-path entry points — every one early-returns on the disabled gate
# and takes no **kwargs (zero allocations when tracing is off)
# ---------------------------------------------------------------------------


def start_trace(name: str, attrs: Optional[Dict[str, Any]] = None
                ) -> Optional[SpanContext]:
    """Open a root span; returns the context to propagate, or ``None``
    when tracing is disabled (call sites guard on that)."""
    rec = _REC
    if rec is None:
        return None
    return rec.start(name, attrs)


def end_trace(ctx: Optional[SpanContext]) -> None:
    """Close a locally-owned trace and run the tail keep/drop decision."""
    rec = _REC
    if rec is None or ctx is None:
        return
    rec.finish(ctx, remote_only=False)


def record(ctx: Optional[SpanContext], name: str, t0: float, t1: float,
           attrs: Optional[Dict[str, Any]] = None,
           parent: Optional[int] = None) -> Optional[int]:
    """Append one completed span ``[t0, t1]`` (monotonic seconds) under
    ``ctx`` — parented to the context span unless ``parent`` names
    another span id.  Returns the new span id (for sub-span parenting),
    or ``None`` if the trace is gone/disabled."""
    rec = _REC
    if rec is None or ctx is None:
        return None
    return rec.record(ctx, name, t0, t1, attrs, parent)


def flag(ctx: Optional[SpanContext], reason: str) -> None:
    """Mark the trace as must-keep (shed / error / deadline bust)."""
    rec = _REC
    if rec is None or ctx is None:
        return
    rec.flag(ctx, reason)


def from_wire(wire) -> Optional[SpanContext]:
    """Re-hydrate a propagated ``(trace_id, span_id)`` tuple.  Joins
    the local trace when the id is known (in-process replica); adopts
    it as a remote-owned fragment otherwise."""
    rec = _REC
    if rec is None or wire is None:
        return None
    if isinstance(wire, SpanContext):
        return rec.adopt(wire.trace_id, wire.span_id)
    try:
        tid, sid = wire
    except (TypeError, ValueError):
        return None
    return rec.adopt(int(tid), int(sid))


def finish_remote(ctx_or_wire) -> None:
    """Finalize a trace fragment this process *adopted* from the wire.
    No-op for locally-rooted traces (their owner's ``end_trace`` runs
    the tail decision) — safe to call unconditionally after replying."""
    rec = _REC
    if rec is None or ctx_or_wire is None:
        return
    ctx = ctx_or_wire
    if not isinstance(ctx, SpanContext):
        # parse the tuple directly — going through from_wire would
        # re-ADOPT a trace the owner already finalized, resurrecting
        # it as an empty fragment
        try:
            tid, sid = ctx_or_wire
        except (TypeError, ValueError):
            return
        ctx = SpanContext(int(tid), int(sid))
    rec.finish(ctx, remote_only=True)


def set_train_context(ctx: Optional[SpanContext]) -> None:
    """Publish the current train step's context for the helpers that
    can't see the loop (fences, PS RPCs, async checkpoint writes)."""
    global _train_ctx
    _train_ctx = ctx


def train_context() -> Optional[SpanContext]:
    if _REC is None:
        return None
    return _train_ctx


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------


def flush(path: Optional[str] = None) -> Optional[str]:
    """Append kept traces as JSONL + Chrome async events; returns the
    path written (``None`` when there was nothing to write)."""
    rec = _REC
    if rec is None:
        return None
    return rec.flush(path)


def drain() -> List[Dict[str, Any]]:
    """Pop kept traces as dicts (test/CLI hook; bypasses the file)."""
    rec = _REC
    if rec is None:
        return []
    return rec.drain()


def stats() -> Dict[str, Any]:
    rec = _REC
    if rec is None:
        return {"enabled": False}
    d = rec.stats()
    d["enabled"] = True
    return d


# -- env gate (mirrors telemetry's import-time switch) -----------------------

if get_env("TRACING", False, bool):
    enable()
