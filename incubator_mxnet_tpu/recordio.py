"""RecordIO (``python/mxnet/recordio.py``, dmlc recordio format).

Binary-compatible with the reference container so ``.rec`` datasets packed
by im2rec interoperate: records framed by magic ``0xced7230a`` + a
length/continue-flag word, 4-byte aligned; ``IRHeader`` (flag, label, id,
id2) prefixes packed items.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple
from typing import Optional

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "IndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img", "scan_record_starts"]

_MAGIC = 0xced7230a
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential record file reader/writer
    (``src/io/ recordio`` capability)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.fp = None
        self.open()

    def open(self):
        # scheme dispatch (dmlc Stream::Create analog): local paths get
        # plain files; http(s)/s3/hdfs URIs get chunked range streams
        # (read-only) — see filesystem.py
        from .filesystem import open_uri

        if self.flag == "w":
            self.fp = open_uri(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fp = open_uri(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("invalid flag %s" % self.flag)

    def close(self):
        if self.fp is not None:
            self.fp.close()
            self.fp = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["fp"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        if self.flag == "w":
            # reopening a writer would TRUNCATE the file already written
            raise MXNetError(
                "cannot unpickle a writable record file (reopening "
                "would truncate %s)" % self.uri)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self) -> int:
        return self.fp.tell()

    def write(self, buf: bytes):
        assert self.writable
        self.fp.write(struct.pack("<I", _MAGIC))
        self.fp.write(struct.pack("<I", len(buf)))
        self.fp.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.fp.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        assert not self.writable
        head = self.fp.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic")
        length = lrec & ((1 << 29) - 1)
        buf = self.fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fp.read(pad)
        return buf


class IndexedRecordIO(MXRecordIO):
    """Random-access record file with a ``.idx`` sidecar
    (reference ``IndexedRecordIO``)."""

    def __init__(self, idx_path: str, uri: str, flag: str,
                 key_type=int):
        import threading

        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        self._rlock = threading.Lock()
        super().__init__(uri, flag)

    def __getstate__(self):
        d = super().__getstate__()
        d.pop("_rlock", None)  # locks don't pickle
        d["fidx"] = None
        return d

    def __setstate__(self, d):
        import threading

        self._rlock = threading.Lock()
        super().__setstate__(d)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        from .filesystem import is_remote

        if self.writable:
            self.fidx = open(self.idx_path, "w")
        elif is_remote(self.idx_path):
            # remote .idx sidecar: tiny text file — one ranged read; a
            # missing sidecar (404 / no such key) falls back to the
            # framing rescan exactly like the local no-idx path
            from .filesystem import open_uri

            from .filesystem import is_not_found

            self.fidx = None
            try:
                with open_uri(self.idx_path, "rb") as f:
                    text = f.read().decode("utf-8")
            except Exception as e:
                # ONLY a missing sidecar falls back to the framing
                # rescan; auth/DNS/timeout errors must surface, not
                # trigger a whole-pack download
                if not is_not_found(e):
                    raise
                cached = getattr(self, "_scan_cache", None)
                if cached is None:
                    cached = scan_record_starts(self.uri)
                    self._scan_cache = cached
                for i, pos in enumerate(cached):
                    key = self.key_type(i)
                    self.idx[key] = pos
                    self.keys.append(key)
                return
            for line in text.splitlines():
                if not line.strip():
                    continue
                parts = line.strip().split("\t")
                key = self.key_type(parts[0])
                self.idx[key] = int(parts[1])
                self.keys.append(key)
        elif not os.path.exists(self.idx_path):
            # no .idx sidecar: rebuild the index by scanning the record
            # framing (native C++ scanner when available — the reference
            # reader was C++ dmlc-core recordio).  Cached: reset() runs
            # close()+open() every epoch and the file cannot change.
            self.fidx = None
            cached = getattr(self, "_scan_cache", None)
            if cached is None:
                cached = scan_record_starts(self.uri)
                self._scan_cache = cached
            for i, pos in enumerate(cached):
                key = self.key_type(i)
                self.idx[key] = pos
                self.keys.append(key)
        else:
            self.fidx = open(self.idx_path, "r")
            for line in self.fidx:
                parts = line.strip().split("\t")
                key = self.key_type(parts[0])
                self.idx[key] = int(parts[1])
                self.keys.append(key)

    def close(self):
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        assert not self.writable
        self.fp.seek(self.idx[idx])

    def read_idx(self, idx):
        # atomic seek+read: threaded consumers (gluon DataLoader prefetch
        # workers) share this handle, and an interleaved seek would make
        # read() consume bytes at the wrong offset
        with self._rlock:
            self.seek(idx)
            return self.read()

    def write_idx(self, idx, buf: bytes):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Prefix data with an IRHeader (multi-label via flag>0)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
        return hdr + s
    label = np.asarray(header.label, dtype=np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s: bytes):
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(s[:flag * 4], dtype=np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header: IRHeader, img, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    """Encode an image array and pack (PNG via pure python; JPEG requires
    cv2/PIL when available)."""
    buf = _encode_img(np.asarray(img), img_fmt, quality)
    return pack(header, buf)


def unpack_img(s: bytes, iscolor=-1):
    header, img_bytes = unpack(s)
    img = _decode_img(img_bytes)
    return header, img


def _encode_img(img: np.ndarray, fmt: str, quality: int) -> bytes:
    try:
        import cv2

        ok, enc = cv2.imencode(fmt, img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        return enc.tobytes()
    except ImportError:
        pass
    try:
        import io as _io

        from PIL import Image

        b = _io.BytesIO()
        Image.fromarray(img.astype(np.uint8)).save(
            b, format="PNG" if "png" in fmt else "JPEG", quality=quality)
        return b.getvalue()
    except ImportError:
        # raw fallback: shape-prefixed uint8 (self-describing)
        hdr = struct.pack("<III", *(img.shape + (1,) * (3 - img.ndim))[:3])
        return b"RAW0" + hdr + img.astype(np.uint8).tobytes()


def _decode_img(buf: bytes) -> np.ndarray:
    if buf[:4] == b"RAW0":
        h, w, c = struct.unpack("<III", buf[4:16])
        return np.frombuffer(buf[16:], dtype=np.uint8).reshape(h, w, c)
    try:
        import cv2

        return cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), -1)
    except ImportError:
        pass
    import io as _io

    from PIL import Image

    return np.asarray(Image.open(_io.BytesIO(buf)))


def scan_record_starts(uri: str):
    """Record START offsets (header position) for every record in a
    ``.rec`` file — native C++ scanner when available, python framing
    walk otherwise."""
    from . import native
    from .filesystem import is_remote, open_uri

    if not is_remote(uri):
        scanned = native.recordio_scan(uri)
        if scanned is not None:
            offsets, _ = scanned
            return [int(o) - 8 for o in offsets]  # payload → header
    starts = []
    with open_uri(uri, "rb") as f:
        if hasattr(f, "size"):
            # RangeStream.size is a property; pyarrow NativeFile.size
            # is a METHOD — handle both
            fsize = f.size() if callable(f.size) else f.size
        else:
            fsize = os.path.getsize(uri)
        while True:
            pos = f.tell()
            head = f.read(8)
            if len(head) < 8:
                break
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise MXNetError("malformed recordio file %s" % uri)
            # upper 3 bits of the length word are the continue flag
            # (dmlc recordio framing) — mask exactly like read()
            length = lrec & ((1 << 29) - 1)
            # a payload running past EOF is a torn tail (writer died
            # mid-record), not a record — same bound as the C scanner
            if pos + 8 + length > fsize:
                break
            starts.append(pos)
            pad = (4 - length % 4) % 4
            f.seek(length + pad, os.SEEK_CUR)
    return starts
