"""Torch interop (reference ``python/mxnet/torch.py``, modernized).

The reference bridged lua-torch tensor functions into the NDArray
namespace (``_th_*`` via the C API).  The modern equivalent exposes
(py)torch over NDArray: ``mx.th.<fn>`` dispatches to ``torch.<fn>`` with
NDArray↔Tensor conversion at the boundary, and ``to_torch``/
``from_torch`` convert explicitly (host roundtrip — torch here is the
CPU build; the TPU compute path stays jax/XLA).
"""
from __future__ import annotations

from typing import Any

from .base import MXNetError
from .ndarray import NDArray
from .ndarray import array as nd_array

__all__ = ["to_torch", "from_torch", "th"]


def _torch():
    try:
        import torch

        return torch
    except ImportError as exc:  # pragma: no cover - torch is baked in
        raise MXNetError("torch is not installed") from exc


def to_torch(arr: NDArray):
    """NDArray → torch.Tensor (host COPY — ``asnumpy`` may return a
    read-only view of the immutable XLA buffer, and an in-place torch op
    on it would corrupt the source array behind jax's back)."""
    import numpy as np

    return _torch().from_numpy(np.array(arr.asnumpy()))


def from_torch(tensor, ctx=None) -> NDArray:
    """torch.Tensor → NDArray."""
    return nd_array(tensor.detach().cpu().numpy(), ctx=ctx)


def _wrap(value: Any):
    torch = _torch()
    if isinstance(value, torch.Tensor):
        return from_torch(value)
    if isinstance(value, (tuple, list)):
        return type(value)(_wrap(v) for v in value)
    return value


class _TorchNamespace:
    """``mx.th.<name>`` → ``torch.<name>`` with boundary conversion
    (the reference registered every ``_th_`` function the same way)."""

    def __getattr__(self, name: str):
        torch = _torch()
        fn = getattr(torch, name, None)
        if fn is None or not callable(fn):
            raise AttributeError("torch has no function %r" % name)

        def wrapped(*args, **kwargs):
            conv = [to_torch(a) if isinstance(a, NDArray) else a
                    for a in args]
            kconv = {k: to_torch(v) if isinstance(v, NDArray) else v
                     for k, v in kwargs.items()}
            return _wrap(fn(*conv, **kconv))

        wrapped.__name__ = name
        wrapped.__doc__ = "torch.%s over NDArray arguments" % name
        return wrapped


th = _TorchNamespace()
