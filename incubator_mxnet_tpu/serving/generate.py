"""Autoregressive generation for the transformer LM: fixed-shape KV
cache + slot-based continuous batching.

The training-side symbol (``models/transformer.py``) is shape-static by
the XLA contract, so naive generation would recompile per sequence
length.  This module keeps the SERVING side shape-static too, vLLM/Orca
style, with exactly two program families:

- **prefill** — one compiled program per (batch-bucket, length-bucket):
  runs the prompt through the stack with causal attention, writes K/V
  into the requests' cache slots, and returns the last-position logits
  (which sample the FIRST new token — TTFT ends here).  Prompts pad to
  a power-of-two length bucket; the padded K/V rows sit beyond the
  prompt length and are never attended (the decode mask is
  ``position <= length``), then get overwritten token by token as
  decode advances — which is also why slot recycling needs no cache
  reset.
- **decode** — ONE compiled program, ever: a single-token step over the
  full slot batch.  Per-slot ``lengths`` drive both the attention mask
  and the scatter position, so sequences of different ages share the
  program.  Finished sequences free their slot and queued prompts join
  the running batch without recompiling — continuous batching.

All deadline and latency math uses ``time.monotonic()`` (never wall
clock, which can step).  The engine's cache layout and admission policy
are overridable hooks (``_setup_cache`` / ``_check_request`` /
``_take_admissible`` / ``_admit`` / ``_decode_batch`` / ``_release``)
— :mod:`.paged` subclasses them to swap the per-slot rectangle for a
paged block pool with prefix caching without touching the loop.

Numerics match the training graph op-for-op (LayerNorm f32 two-pass
stats, FullyConnected ``x·Wᵀ+b``, max-subtract softmax attention):
``tests/test_serving.py`` asserts decode logits equal the full-sequence
symbol forward within 1e-5.  Sampling reuses the registered ops —
``ops/ordering.py`` ``topk`` and ``_sample_multinomial`` — under greedy
/ temperature / top-k policies.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from .. import telemetry, tracing
from ..analysis.race_checker import race_audit
from ..base import MXNetError, get_env
from .engine import ServeStats, bucket_batch, bucket_length

__all__ = ["LMSpec", "KVTransformerLM", "GenerationEngine",
           "GenerationResult"]


class LMSpec:
    """Architecture of a ``models.transformer_lm`` checkpoint, inferred
    from parameter shapes (heads cannot be inferred — pass it)."""

    __slots__ = ("vocab_size", "embed", "heads", "num_layers", "max_seq",
                 "fused_qkv", "head_bias")

    def __init__(self, vocab_size, embed, heads, num_layers, max_seq,
                 fused_qkv=False, head_bias=True):
        if embed % heads:
            raise MXNetError("embed (%d) must divide by heads (%d)"
                             % (embed, heads))
        self.vocab_size = vocab_size
        self.embed = embed
        self.heads = heads
        self.num_layers = num_layers
        self.max_seq = max_seq
        self.fused_qkv = fused_qkv
        self.head_bias = head_bias

    @property
    def head_dim(self):
        return self.embed // self.heads

    @classmethod
    def from_params(cls, params: Dict[str, np.ndarray],
                    heads: int) -> "LMSpec":
        def shape(name):
            v = params.get(name)
            if v is None:
                raise MXNetError(
                    "parameter %r missing: not a transformer_lm "
                    "checkpoint (have %s...)" % (name,
                                                 sorted(params)[:6]))
            return tuple(np.asarray(
                v.data if hasattr(v, "data") else v).shape)

        if any("_moe_" in n for n in params):
            raise MXNetError("serving supports the dense-FFN transformer "
                             "family; MoE generation is not implemented")
        vocab, embed = shape("tok_embed_weight")
        max_seq = shape("pos_embed_weight")[0]
        layers = 0
        while ("block%d_ln1_gamma" % layers) in params:
            layers += 1
        if not layers:
            raise MXNetError("no transformer blocks found in params")
        fused_qkv = "block0_qkv_weight" in params
        head_bias = "lm_head_bias" in params
        return cls(vocab, embed, heads, layers, max_seq,
                   fused_qkv=fused_qkv, head_bias=head_bias)


def _ln(x, gamma, beta, eps=1e-5):
    import jax
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta


def _fc(x, w, b=None):
    import jax.numpy as jnp

    from ..quant.int8 import Int8Weight, int8_matmul

    if isinstance(w, Int8Weight):
        # weight-only int8: dequant fused into the matmul epilogue
        # (docs/quantization.md) — decode reads int8 weight bytes
        y = int8_matmul(x, w)
    else:
        y = jnp.matmul(x, w.T)
    return y if b is None else y + b


# transformer_lm weights that feed matmuls (quantizable); the embedding
# tables are gathers — dequantizing a whole vocab table per step would
# cost more bytes than it saves
_EMBED_WEIGHTS = ("tok_embed_weight", "pos_embed_weight")

_KV_DTYPES = ("float32", "bfloat16", "float16")


class KVTransformerLM:
    """Pure-jax twin of the ``models/transformer.py`` forward with a
    fixed-shape KV cache, built from a trained ``arg_params`` dict.

    The cache is a pair of ``(slots, layers, heads, max_len, head_dim)``
    arrays threaded functionally through the compiled steps (donated
    back by the engine).  Per-shape program bookkeeping lives in
    ``self.stats`` so callers can assert the compile bound.
    """

    def __init__(self, arg_params: Dict, heads: int,
                 spec: Optional[LMSpec] = None, *,
                 weight_dtype: Optional[str] = None,
                 kv_dtype: Optional[str] = None):
        import jax

        # int8 weight-only quantization (TP_SERVE_WEIGHT_DTYPE=int8):
        # matmul weights stored int8 + per-output-channel scale, ONCE at
        # load; embeddings/norms/biases stay f32 (docs/quantization.md)
        if weight_dtype is None:
            weight_dtype = get_env("SERVE_WEIGHT_DTYPE") or None
        if weight_dtype in ("", "float32", "f32"):
            weight_dtype = None
        if weight_dtype not in (None, "int8"):
            raise MXNetError("weight_dtype must be None or 'int8', "
                             "got %r" % (weight_dtype,))
        self.weight_dtype = weight_dtype
        # KV-cache storage dtype (TP_KV_DTYPE): bf16 halves cache HBM;
        # attention still accumulates in f32 (reads upcast, writes cast)
        if kv_dtype is None:
            kv_dtype = get_env("KV_DTYPE", "float32")
        if not kv_dtype:
            kv_dtype = "float32"
        if kv_dtype not in _KV_DTYPES:
            raise MXNetError("kv_dtype must be one of %s, got %r"
                             % (_KV_DTYPES, kv_dtype))
        self.kv_dtype = kv_dtype

        self.spec = spec or LMSpec.from_params(arg_params, heads)
        self.params = {}
        weight_bytes = 0
        for n, v in arg_params.items():
            a = np.asarray(v.data if hasattr(v, "data") else v)
            if a.dtype != np.float32:
                a = a.astype(np.float32)
            if (weight_dtype == "int8" and a.ndim == 2
                    and n.endswith("_weight")
                    and not n.endswith(_EMBED_WEIGHTS)):
                from ..quant.int8 import Int8Weight, quantize_rowwise

                q, scale = quantize_rowwise(a)
                w = Int8Weight(jax.device_put(q), jax.device_put(scale))
                self.params[n] = w
                weight_bytes += w.nbytes
            else:
                self.params[n] = jax.device_put(a)
                weight_bytes += a.nbytes
        # what actually sits in HBM for params — the int8 win shows here
        self.weight_bytes = weight_bytes
        telemetry.gauge("quant_weight_bytes",
                        {"component": "kv_lm"}).set(weight_bytes)
        self.stats = ServeStats()
        self._prefill_fns = {}
        self._decode_fn = None
        self._verify_fns = {}
        self._sample_fns = {}

    # ----------------------------------------------------------- cache setup
    def init_cache(self, num_slots: int, max_len: int):
        """Allocate the fixed-shape cache: one scratch slot is appended
        at index ``num_slots`` so padded prefill rows have a harmless
        scatter target."""
        import jax.numpy as jnp

        s = self.spec
        if max_len > s.max_seq:
            raise MXNetError(
                "max_len %d exceeds the model's position table (%d)"
                % (max_len, s.max_seq))
        shape = (num_slots + 1, s.num_layers, s.heads, max_len,
                 s.head_dim)
        from ..base import dtype_np

        dt = dtype_np(self.kv_dtype)
        return jnp.zeros(shape, dt), jnp.zeros(shape, dt)

    # ------------------------------------------------------------- internals
    def _embed(self, tokens, positions):
        import jax.numpy as jnp

        p = self.params
        tok = jnp.take(p["tok_embed_weight"], tokens, axis=0)
        pos = jnp.take(p["pos_embed_weight"], positions, axis=0)
        return tok + pos

    def _qkv(self, i, h):
        """Project ``h`` (..., E) to per-head q, k, v (..., H, D)."""
        import jax.numpy as jnp

        p, s = self.params, self.spec
        E = s.embed
        if s.fused_qkv:
            p3 = _fc(h, p["block%d_qkv_weight" % i])
            parts = [p3[..., j * E:(j + 1) * E] for j in range(3)]
        else:
            parts = [_fc(h, p["block%d_%s_weight" % (i, w)])
                     for w in ("q", "k", "v")]
        return [jnp.reshape(a, a.shape[:-1] + (s.heads, s.head_dim))
                for a in parts]

    def _ffn(self, i, x):
        import jax

        p = self.params
        h = _ln(x, p["block%d_ln2_gamma" % i], p["block%d_ln2_beta" % i])
        h = jax.nn.relu(_fc(h, p["block%d_ffn1_weight" % i],
                            p["block%d_ffn1_bias" % i]))
        return x + _fc(h, p["block%d_ffn2_weight" % i],
                       p["block%d_ffn2_bias" % i])

    def _head(self, x):
        p = self.params
        return _fc(x, p["lm_head_weight"],
                   p.get("lm_head_bias") if self.spec.head_bias else None)

    def _attn_out(self, i, att, x):
        """Merge heads, project, add residual.  ``att`` (..., H, D)."""
        import jax.numpy as jnp

        p, s = self.params, self.spec
        merged = jnp.reshape(att, att.shape[:-2] + (s.embed,))
        return x + _fc(merged, p["block%d_attn_proj_weight" % i],
                       p["block%d_attn_proj_bias" % i])

    # --------------------------------------------------------------- prefill
    def _build_prefill(self):
        import jax
        import jax.numpy as jnp

        s = self.spec
        scale = 1.0 / s.head_dim ** 0.5
        neg = jnp.finfo(jnp.float32).min

        def prefill(cache_k, cache_v, tokens, lengths, slots):
            # tokens (N, L) int32; lengths/slots (N,) int32
            N, L = tokens.shape
            x = self._embed(tokens, jnp.arange(L)[None, :])  # (N, L, E)
            causal = (jnp.arange(L)[:, None] >= jnp.arange(L)[None, :])
            ks, vs = [], []
            for i in range(s.num_layers):
                h = _ln(x, self.params["block%d_ln1_gamma" % i],
                        self.params["block%d_ln1_beta" % i])
                q, k, v = self._qkv(i, h)          # (N, L, H, D)
                q = jnp.moveaxis(q, 1, 2)          # (N, H, L, D)
                k = jnp.moveaxis(k, 1, 2)
                v = jnp.moveaxis(v, 1, 2)
                sc = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
                sc = jnp.where(causal, sc, neg)
                w = jax.nn.softmax(sc, axis=-1)
                att = jnp.einsum("nhqk,nhkd->nhqd", w, v)
                att = jnp.moveaxis(att, 1, 2)      # (N, L, H, D)
                x = self._attn_out(i, att, x)
                x = self._ffn(i, x)
                ks.append(k)
                vs.append(v)
            # one scatter per cache: (N, layers, H, L, D) into the slot
            # rows' first L positions
            knew = jnp.stack(ks, axis=1)
            vnew = jnp.stack(vs, axis=1)
            cache_k = cache_k.at[slots, :, :, :L, :].set(
                knew.astype(cache_k.dtype))
            cache_v = cache_v.at[slots, :, :, :L, :].set(
                vnew.astype(cache_v.dtype))
            x = _ln(x, self.params["ln_f_gamma"],
                    self.params["ln_f_beta"])
            last = jnp.take_along_axis(
                x, (lengths - 1)[:, None, None], axis=1)[:, 0]  # (N, E)
            return cache_k, cache_v, self._head(last)

        return prefill

    def prefill(self, cache_k, cache_v, tokens: np.ndarray,
                lengths: np.ndarray, slots: np.ndarray):
        """Run one padded prompt bucket.  ``tokens`` (N, L) with N and L
        already bucketed; returns (cache_k, cache_v, last_logits)."""
        import jax
        import jax.numpy as jnp

        N, L = tokens.shape
        fn = self._prefill_fns.get((N, L))
        if fn is None:
            fn = jax.jit(self._build_prefill())
            self._prefill_fns[(N, L)] = fn
        self.stats.record_batch(("prefill", N, L),
                                int((np.asarray(lengths) > 0).sum()), N,
                                "prefill")
        # jnp.array (not asarray): jax on CPU may alias numpy buffers
        # zero-copy, and dispatch is async — a caller mutating its
        # lengths/tokens array after this call would race the compute.
        return fn(cache_k, cache_v,
                  jnp.array(tokens, jnp.int32),
                  jnp.array(lengths, jnp.int32),
                  jnp.array(slots, jnp.int32))

    # ---------------------------------------------------------------- decode
    def _build_decode(self):
        import jax
        import jax.numpy as jnp

        s = self.spec
        scale = 1.0 / s.head_dim ** 0.5
        neg = jnp.finfo(jnp.float32).min

        def decode(cache_k, cache_v, tokens, lengths):
            # tokens/lengths (slots,) int32: the new token per slot sits
            # at position `lengths` and attends to cached j < lengths
            # plus itself — softmax over the concat matches a full
            # causal row bit-for-bit in f32 tolerance.
            nslots = tokens.shape[0]
            S = cache_k.shape[3]
            x = self._embed(tokens, lengths)               # (slots, E)
            mask = (jnp.arange(S)[None, :]
                    < lengths[:, None])[:, None, :]        # (slots,1,S)
            ks, vs = [], []
            for i in range(s.num_layers):
                h = _ln(x, self.params["block%d_ln1_gamma" % i],
                        self.params["block%d_ln1_beta" % i])
                q, k, v = self._qkv(i, h)                  # (slots, H, D)
                # reads upcast: attention accumulates in f32 even when
                # the cache stores bf16 (TP_KV_DTYPE)
                kc = cache_k[:nslots, i].astype(jnp.float32)
                vc = cache_v[:nslots, i].astype(jnp.float32)
                sc = jnp.einsum("nhd,nhkd->nhk", q, kc) * scale
                sc = jnp.where(mask, sc, neg)
                s_self = jnp.einsum("nhd,nhd->nh", q, k) * scale
                full = jnp.concatenate([sc, s_self[..., None]], axis=-1)
                w = jax.nn.softmax(full, axis=-1)
                att = jnp.einsum("nhk,nhkd->nhd", w[..., :S], vc) \
                    + w[..., S, None] * v
                x = self._attn_out(i, att, x)
                x = self._ffn(i, x)
                ks.append(k)
                vs.append(v)
            knew = jnp.stack(ks, axis=1)        # (slots, layers, H, D)
            vnew = jnp.stack(vs, axis=1)
            rows = jnp.arange(nslots)
            pos = jnp.minimum(lengths, S - 1)
            cache_k = cache_k.at[rows, :, :, pos, :].set(
                knew.astype(cache_k.dtype))
            cache_v = cache_v.at[rows, :, :, pos, :].set(
                vnew.astype(cache_v.dtype))
            x = _ln(x, self.params["ln_f_gamma"],
                    self.params["ln_f_beta"])
            return cache_k, cache_v, self._head(x)

        return decode

    def decode(self, cache_k, cache_v, tokens: np.ndarray,
               lengths: np.ndarray):
        """One single-token step over the full slot batch (the ONE
        compiled decode program)."""
        import jax
        import jax.numpy as jnp

        if self._decode_fn is None:
            self._decode_fn = jax.jit(self._build_decode())
        n = int(np.asarray(tokens).shape[0])
        self.stats.record_batch(("decode", n), n, n, "decode")
        # forced copy: see prefill() — callers mutate lengths in place
        # between steps and CPU jax may alias numpy buffers zero-copy
        return self._decode_fn(cache_k, cache_v,
                               jnp.array(tokens, jnp.int32),
                               jnp.array(lengths, jnp.int32))

    # ---------------------------------------------------------------- verify
    def _build_verify(self):
        import jax
        import jax.numpy as jnp

        s = self.spec
        scale = 1.0 / s.head_dim ** 0.5
        neg = jnp.finfo(jnp.float32).min

        def verify(cache_k, cache_v, tokens, lengths, slots):
            # tokens (N, M) int32: M candidate continuation tokens per
            # row starting at cache position `lengths`; lengths/slots
            # (N,) int32.  Each candidate attends the cached prefix
            # (masked by `lengths`, like decode) plus the candidates at
            # or before it (causal among the M) — ONE softmax over the
            # concat, so the masked lanes underflow to exactly 0 and
            # each row matches the sequential decode step bit-for-bit
            # (same argument as the paged suffix prefill).
            N, M = tokens.shape
            S = cache_k.shape[3]
            positions = lengths[:, None] + jnp.arange(M)[None, :]
            x = self._embed(tokens,
                            jnp.minimum(positions, s.max_seq - 1))
            cmask = (jnp.arange(S)[None, :]
                     < lengths[:, None])[:, None, None, :]  # (N,1,1,S)
            causal = (jnp.arange(M)[:, None]
                      >= jnp.arange(M)[None, :])            # (M, M)
            ks, vs = [], []
            for i in range(s.num_layers):
                h = _ln(x, self.params["block%d_ln1_gamma" % i],
                        self.params["block%d_ln1_beta" % i])
                q, k, v = self._qkv(i, h)          # (N, M, H, D)
                qh = jnp.moveaxis(q, 1, 2)         # (N, H, M, D)
                kh = jnp.moveaxis(k, 1, 2)
                vh = jnp.moveaxis(v, 1, 2)
                # reads upcast (bf16 KV accumulates in f32, see decode)
                kc = cache_k[slots, i].astype(jnp.float32)  # (N,H,S,D)
                vc = cache_v[slots, i].astype(jnp.float32)
                spre = jnp.einsum("nhqd,nhkd->nhqk", qh, kc) * scale
                spre = jnp.where(cmask, spre, neg)
                sself = jnp.einsum("nhqd,nhkd->nhqk", qh, kh) * scale
                sself = jnp.where(causal, sself, neg)
                w = jax.nn.softmax(
                    jnp.concatenate([spre, sself], axis=-1), axis=-1)
                att = jnp.einsum("nhqk,nhkd->nhqd", w[..., :S], vc) \
                    + jnp.einsum("nhqk,nhkd->nhqd", w[..., S:], vh)
                att = jnp.moveaxis(att, 1, 2)      # (N, M, H, D)
                x = self._attn_out(i, att, x)
                x = self._ffn(i, x)
                ks.append(k)
                vs.append(v)
            # scatter ALL M candidate K/V rows: acceptance is decided on
            # the host AFTER this pass, and rollback is free — the mask
            # is `position < length`, so rejected positions are simply
            # never attended and get overwritten by later writes
            knew = jnp.stack(ks, axis=2)     # (N, M, layers, H, D)
            vnew = jnp.stack(vs, axis=2)
            pos = jnp.minimum(positions, S - 1)          # (N, M)
            cache_k = cache_k.at[slots[:, None], :, :, pos, :].set(
                knew.astype(cache_k.dtype))
            cache_v = cache_v.at[slots[:, None], :, :, pos, :].set(
                vnew.astype(cache_v.dtype))
            x = _ln(x, self.params["ln_f_gamma"],
                    self.params["ln_f_beta"])
            return cache_k, cache_v, self._head(x)   # logits (N, M, V)

        return verify

    def verify(self, cache_k, cache_v, tokens: np.ndarray,
               lengths: np.ndarray, slots: np.ndarray):
        """Score M candidate positions per slot in ONE compiled pass
        (the speculative-decoding verify step; also the rectangular
        chunked-prefill continuation).  ``tokens`` (N, M); returns
        (cache_k, cache_v, logits (N, M, vocab))."""
        import jax
        import jax.numpy as jnp

        N, M = tokens.shape
        fn = self._verify_fns.get((N, M))
        if fn is None:
            fn = jax.jit(self._build_verify())
            self._verify_fns[(N, M)] = fn
        self.stats.record_batch(("verify", N, M), N, N, "verify")
        # forced copy: see prefill() — callers mutate lengths in place
        return fn(cache_k, cache_v,
                  jnp.array(tokens, jnp.int32),
                  jnp.array(lengths, jnp.int32),
                  jnp.array(slots, jnp.int32))

    # --------------------------------------------------------------- oracles
    def full_logits(self, tokens: np.ndarray) -> np.ndarray:
        """Full-sequence forward (no cache): the parity oracle.  Returns
        (B, L, vocab) logits."""
        import jax
        import jax.numpy as jnp

        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        B, L = tokens.shape
        key = (B, L, "full")
        fn = self._prefill_fns.get(key)
        if fn is None:
            fn = jax.jit(lambda t: _all_logits(self, t))
            self._prefill_fns[key] = fn
        return np.asarray(fn(jnp.asarray(tokens, jnp.int32)))

    # -------------------------------------------------------------- sampling
    def sample(self, logits, key, temperature: float = 0.0,
               top_k: int = 0):
        """Sample next tokens from (n, vocab) logits.  ``temperature<=0``
        is greedy argmax; otherwise softmax sampling through the
        registered ``_sample_multinomial`` op, optionally truncated to
        the ``topk`` op's top-k candidates."""
        import jax

        cfg = (float(temperature), int(top_k),
               tuple(np.asarray(logits).shape))
        fn = self._sample_fns.get(cfg)
        if fn is None:
            fn = jax.jit(_build_sample(float(temperature), int(top_k)))
            self._sample_fns[cfg] = fn
            with self.stats.lock:
                self.stats.compile_keys.add(("sample",) + cfg)
            telemetry.counter("serve_compiles_total",
                              {"phase": "sample"}).inc()
        return np.asarray(fn(logits, key)).astype(np.int32)


def _all_logits(model: KVTransformerLM, tokens):
    """Trace the full causal forward, returning logits at EVERY
    position (the test/bench oracle; same math as prefill)."""
    import jax
    import jax.numpy as jnp

    s = model.spec
    scale = 1.0 / s.head_dim ** 0.5
    neg = jnp.finfo(jnp.float32).min
    B, L = tokens.shape
    x = model._embed(tokens, jnp.arange(L)[None, :])
    causal = (jnp.arange(L)[:, None] >= jnp.arange(L)[None, :])
    for i in range(s.num_layers):
        h = _ln(x, model.params["block%d_ln1_gamma" % i],
                model.params["block%d_ln1_beta" % i])
        q, k, v = model._qkv(i, h)
        q = jnp.moveaxis(q, 1, 2)
        k = jnp.moveaxis(k, 1, 2)
        v = jnp.moveaxis(v, 1, 2)
        sc = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
        sc = jnp.where(causal, sc, neg)
        w = jax.nn.softmax(sc, axis=-1)
        att = jnp.moveaxis(jnp.einsum("nhqk,nhkd->nhqd", w, v), 1, 2)
        x = model._attn_out(i, att, x)
        x = model._ffn(i, x)
    x = _ln(x, model.params["ln_f_gamma"], model.params["ln_f_beta"])
    return model._head(x)


def _build_sample(temperature: float, top_k: int):
    """Sampling kernel over (n, vocab) logits reusing the registered
    ordering/random ops (ISSUE contract: one source of truth for topk
    and multinomial semantics)."""
    import jax
    import jax.numpy as jnp

    from ..ops.registry import OpContext, get_op

    topk_op = get_op("topk")
    multinomial = get_op("_sample_multinomial")

    def fn(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits / temperature
        if top_k:
            outs, _ = topk_op.apply(
                [scaled], {"k": str(top_k), "ret_typ": "both",
                           "axis": "-1"}, OpContext())
            vals, idx = outs
            probs = jax.nn.softmax(vals, axis=-1)
            picked, _ = multinomial.apply(
                [probs], {}, OpContext(rng=key))
            pick = picked[0].astype(jnp.int32)
            return jnp.take_along_axis(
                idx.astype(jnp.int32), pick[:, None], axis=-1)[:, 0]
        probs = jax.nn.softmax(scaled, axis=-1)
        picked, _ = multinomial.apply([probs], {}, OpContext(rng=key))
        return picked[0].astype(jnp.int32)

    return fn


class GenerationResult:
    """Outcome of one generation request."""

    __slots__ = ("tokens", "logits", "prompt_len", "slot", "ttft_s")

    def __init__(self, tokens, logits, prompt_len, slot, ttft_s):
        self.tokens = tokens          # (n_generated,) int32
        self.logits = logits          # (n_generated, vocab) f32 or None
        self.prompt_len = prompt_len
        self.slot = slot
        self.ttft_s = ttft_s


class _GenPending:
    __slots__ = ("tokens", "max_new", "temperature", "top_k",
                 "stop_token", "return_logits", "deadline", "t_submit",
                 "future", "slot", "shared_tokens", "trace")

    def __init__(self, tokens, max_new, temperature, top_k, stop_token,
                 return_logits, deadline, future):
        self.tokens = tokens
        self.max_new = max_new
        self.temperature = temperature
        self.top_k = top_k
        self.stop_token = stop_token
        self.return_logits = return_logits
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.future = future
        # filled at admission time (paged engine: reserved slot and
        # shared-prefix token count)
        self.slot = None
        self.shared_tokens = 0
        self.trace = None        # tracing.SpanContext (from the wire)


class _Seq:
    """One running sequence occupying a cache slot."""

    __slots__ = ("req", "slot", "length", "last_token", "generated",
                 "logits", "t_first", "t_last", "t_cursor")

    def __init__(self, req, slot, prompt_len):
        self.req = req
        self.slot = slot
        self.length = prompt_len     # tokens with K/V in cache... + self
        self.last_token = None       # newest sampled token (no K/V yet)
        self.generated: List[int] = []
        self.logits: List[np.ndarray] = []
        self.t_first = None
        self.t_last = None
        # phase cursor for tracing: each recorded phase span starts
        # where the previous one ended, so a trace's queue + prefill +
        # decode-tick durations sum to the engine-observed latency by
        # construction (docs/tracing.md)
        self.t_cursor = req.t_submit

    @property
    def done(self):
        if len(self.generated) >= self.req.max_new:
            return True
        return (self.req.stop_token is not None and self.generated
                and self.generated[-1] == self.req.stop_token)


# exempt mirrors the static suppressions: the slot tables and the KV
# cache handles are loop-thread-owned after __init__ (Thread.start is
# the happens-before edge; active_slots is an advisory cross-thread
# scan) and the public counters are monitoring mirrors whose unlocked
# external reads are by design
@race_audit(exempt=("_seqs", "_lengths", "_cache_k", "_cache_v",
                    "_key", "prefill_tokens", "active_high_water"))
class GenerationEngine:
    """Continuous-batching generation server over a
    :class:`KVTransformerLM`.

    ``submit`` enqueues a prompt and returns a Future resolving to a
    :class:`GenerationResult`.  A background loop interleaves (a)
    admitting queued prompts into free cache slots via bucketed prefill
    and (b) single-token decode steps over every running slot — new
    arrivals join the running batch between steps, finished sequences
    free their slot immediately (Orca iteration-level scheduling).
    """

    def __init__(self, model: KVTransformerLM, *,
                 max_slots: Optional[int] = None,
                 max_len: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 seed: int = 0, name: str = "serve_lm"):
        import jax

        self.model = model
        self.max_slots = int(max_slots if max_slots is not None
                             else get_env("SERVE_SLOTS", 8, int))
        self.max_len = int(max_len if max_len is not None
                           else model.spec.max_seq)
        self.max_queue = int(max_queue if max_queue is not None
                             else get_env("SERVE_MAX_QUEUE", 256, int))
        self.name = name
        self.stats = model.stats
        # engine-local mirrors (ServeStats is per-model and may be
        # shared by several engines, e.g. an A/B bench)
        self.active_high_water = 0
        self.prefill_tokens = 0
        # EWMA of completed-request wall time (written by the loop
        # thread in _finish, read by load_report — both under _cond):
        # the router's deadline-shedding ETA estimate
        self._req_ewma = 0.0
        self._setup_cache()
        self._seqs: List[Optional[_Seq]] = [None] * self.max_slots
        self._lengths = np.zeros(self.max_slots, np.int32)
        self._pending: List[_GenPending] = []
        self._key = jax.random.PRNGKey(seed)
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=name + "-decode", daemon=True)
        self._thread.start()

    # ----------------------------------------------------- overridable hooks
    def _setup_cache(self) -> None:
        """Allocate the KV storage (hook: the paged engine swaps the
        per-slot rectangle for a block pool)."""
        self._cache_k, self._cache_v = self.model.init_cache(
            self.max_slots, self.max_len)

    def _spec_reserve_extra(self) -> int:
        """Cache positions a request may transiently need beyond
        ``prompt + max_new`` (hook: the speculative engine returns k —
        a verify pass writes k candidate K/V rows past the accepted
        length, and the reservation must cover the worst case so no
        mid-speculation allocation can fail)."""
        return 0

    def _check_request(self, tokens: np.ndarray, max_new: int) -> None:
        """Reject a request that could NEVER be admitted (hook: the
        paged engine adds a page-budget bound)."""
        extra = self._spec_reserve_extra()
        if tokens.size + max_new + extra > self.max_len:
            raise MXNetError(
                "prompt (%d) + max_new_tokens (%d)%s exceeds the "
                "engine's max_len (%d)"
                % (tokens.size, max_new,
                   " + speculative headroom (%d)" % extra if extra
                   else "", self.max_len))

    # ------------------------------------------------------------ client API
    def submit(self, tokens, max_new_tokens: int = 16, *,
               temperature: float = 0.0, top_k: int = 0,
               stop_token: Optional[int] = None,
               return_logits: bool = False,
               deadline_ms: Optional[float] = None,
               trace_ctx=None) -> Future:
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size < 1:
            raise MXNetError("empty prompt")
        self._check_request(tokens, int(max_new_tokens))
        fut: Future = Future()
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _GenPending(tokens, int(max_new_tokens), temperature,
                          int(top_k), stop_token, return_logits,
                          deadline, fut)
        if trace_ctx is not None:
            # join (in-process fleet) or adopt (remote replica) the
            # propagated trace; None when tracing is disabled here
            req.trace = tracing.from_wire(trace_ctx)
        with self._cond:
            if self._closed:
                raise MXNetError("engine %r is closed" % self.name)
            if len(self._pending) >= self.max_queue:
                with self.stats.lock:
                    self.stats.rejected += 1
                telemetry.counter("serve_rejected_total").inc()
                raise MXNetError(
                    "serve queue full (%d >= max_queue=%d): backpressure"
                    % (len(self._pending), self.max_queue))
            self._pending.append(req)
            telemetry.gauge("serve_queue_depth").set(len(self._pending))
            self._cond.notify_all()
        return fut

    def generate(self, tokens, max_new_tokens: int = 16,
                 timeout: Optional[float] = 120.0,
                 **kw) -> GenerationResult:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(tokens, max_new_tokens, **kw).result(
            timeout=timeout)

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending, self._pending = self._pending, []
            self._cond.notify_all()
        for p in pending:
            p.future.set_exception(
                MXNetError("engine %r closed" % self.name))
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def active_slots(self) -> int:
        return sum(1 for s in self._seqs if s is not None)

    # --------------------------------------------------------------- probe
    def load_report(self) -> Dict[str, object]:
        """Cheap lock-safe load snapshot: the fleet router's heartbeat
        probe (docs/fleet_serving.md).

        Engine queue/slot state is read under ``_cond`` and the stats
        mirrors via :meth:`ServeStats.snapshot` (under ``stats.lock``)
        — never field-by-field unlocked, so the router always sees a
        consistent picture.  The paged engine overrides this to add
        real page occupancy and the pool's registered prefix digests
        (the router's placement key); the rectangular engine reports
        slots as pages so the router's capacity math stays uniform, and
        its empty digest tuple disables prefix scoring for it.
        """
        st = self.stats.snapshot()
        with self._cond:
            queued = len(self._pending)
            active = sum(1 for s in self._seqs if s is not None)
            closed = self._closed
            est = self._req_ewma
        free = max(0, self.max_slots - active)
        report: Dict[str, object] = {
            "name": self.name,
            "closed": closed,
            "max_slots": self.max_slots,
            "max_len": self.max_len,
            "active_slots": active,
            "free_slots": free,
            "queue_depth": queued,
            "est_request_s": est,
            "requests": st["requests"],
            "spec_accept_rate": st["spec_accept_rate"],
            "num_compiles": st["num_compiles"],
            "page_tokens": 0,
            "free_pages": free,
            "total_pages": self.max_slots,
            "prefix_digests": (),
        }
        telemetry.gauge("serve_free_slots").set(free)
        telemetry.gauge("serve_active_slots").set(active)
        return report

    # ------------------------------------------------------------- the loop
    def _next_key(self):
        import jax

        self._key, sub = jax.random.split(self._key)
        return sub

    def _expire_pending(self, now: float) -> None:
        alive = []
        for p in self._pending:
            if p.deadline is not None and now > p.deadline:
                with self.stats.lock:
                    self.stats.expired += 1
                telemetry.counter("serve_deadline_expired_total").inc()
                if p.trace is not None:
                    tracing.flag(p.trace, "deadline")
                    tracing.record(p.trace, "serve.queue",
                                   p.t_submit, now)
                p.future.set_exception(MXNetError(
                    "request deadline expired after %.1f ms in queue"
                    % ((now - p.t_submit) * 1e3)))
            else:
                alive.append(p)
        self._pending[:] = alive

    def _loop(self) -> None:
        while True:
            with self._cond:
                self._expire_pending(time.monotonic())
                has_work = (self._pending
                            and self.active_slots < self.max_slots) \
                    or self.active_slots > 0
                if not has_work:
                    if self._closed:
                        if self.active_slots == 0:
                            return
                    else:
                        self._cond.wait(timeout=0.1)
                        continue
                admitted = self._take_admissible()
            try:
                if admitted:
                    self._admit(admitted)
                if self.active_slots:
                    self._decode_step()
            except Exception as e:  # noqa: BLE001 — fail the sequences
                self._fail_all(e)
                # requests admitted but not yet seated in a slot (the
                # failure hit _admit before the slot assignment) are
                # invisible to _fail_all — fail them too or their
                # futures hang forever
                for r in admitted:
                    if not r.future.done():
                        r.future.set_exception(e)

    def _take_admissible(self) -> List[_GenPending]:
        """Pull as many pending requests as there are free slots (must
        hold the lock)."""
        free = self.max_slots - self.active_slots
        take, self._pending = (self._pending[:free],
                               self._pending[free:])
        telemetry.gauge("serve_queue_depth").set(len(self._pending))
        return take

    def _fail_all(self, exc: Exception) -> None:
        for i, seq in enumerate(self._seqs):
            if seq is not None:
                # release BEFORE failing the future: a waiter woken by
                # the exception must observe the slot/pages as free
                self._release(i)
                seq.req.future.set_exception(exc)

    def _abort_admission(self, req: _GenPending) -> None:
        """Drop any reservation made for a request at admission time
        that will never be seated in a slot (hook: the paged engine
        returns the request's reserved KV pages to the pool)."""

    def _release(self, slot: int) -> None:
        """Free a slot (hook: the paged engine also returns its KV
        pages to the pool).  Zeroing the mask length is the stale-KV
        invalidation: the decode mask is ``position < length``, so a
        recycled slot can never attend the previous occupant's K/V —
        whatever bytes remain in the cache are unreachable until
        overwritten."""
        self._seqs[slot] = None
        self._lengths[slot] = 0

    # -------------------------------------------------------------- admit
    def _admit(self, reqs: List[_GenPending]) -> None:
        """Prefill newcomers into free slots, bucketed by prompt-length
        then batch power of two; sample their first token (TTFT)."""
        free = [i for i, s in enumerate(self._seqs) if s is None]
        groups: Dict[int, List[_GenPending]] = {}
        for r in reqs:
            L = bucket_length(r.tokens.size, self.max_len)
            groups.setdefault(L, []).append(r)
        for L, group in sorted(groups.items()):
            while group:
                chunk = group[:len(free)]
                group = group[len(free):]
                n = len(chunk)
                nb = bucket_batch(n, self.max_slots)
                toks = np.zeros((nb, L), np.int32)
                lens = np.ones(nb, np.int32)
                # padding rows target the scratch slot (index
                # max_slots) so their K/V writes land nowhere real
                slots = np.full(nb, self.max_slots, np.int32)
                for j, r in enumerate(chunk):
                    toks[j, :r.tokens.size] = r.tokens
                    lens[j] = r.tokens.size
                    slots[j] = free[j]
                npref = int(sum(r.tokens.size for r in chunk))
                with self._cond:
                    self.prefill_tokens += npref
                telemetry.counter("serve_prefill_tokens_total").inc(npref)
                t_p0 = time.monotonic()
                self._cache_k, self._cache_v, logits = \
                    self.model.prefill(self._cache_k, self._cache_v,
                                       toks, lens, slots)
                logits = np.asarray(logits)
                now = time.monotonic()
                for j, r in enumerate(chunk):
                    seq = _Seq(r, free[j], r.tokens.size)
                    # tp-lint: disable=race-unlocked-shared-state -- loop-owned; advisory scan
                    self._seqs[free[j]] = seq
                    self._lengths[free[j]] = r.tokens.size
                    if r.trace is not None:
                        tracing.record(r.trace, "serve.queue",
                                       r.t_submit, t_p0)
                        tracing.record(r.trace, "serve.prefill",
                                       t_p0, now,
                                       {"tokens": int(r.tokens.size),
                                        "bucket": int(L)})
                        seq.t_cursor = now
                    self._emit(seq, logits[j], now)
                free = free[n:]

    def _emit(self, seq: _Seq, logits_row: np.ndarray,
              now: float) -> None:
        """Sample one token for ``seq`` from its logits row, record
        latency metrics, and retire the sequence if finished."""
        tok = int(self.model.sample(
            logits_row[None], self._next_key(),
            temperature=seq.req.temperature, top_k=seq.req.top_k)[0])
        self._emit_run(seq, [tok], [logits_row], now)

    def _emit_run(self, seq: _Seq, toks, logits_rows,
                  now: float, finish: bool = True) -> int:
        """Append a run of already-sampled tokens to ``seq`` —
        truncating at ``max_new`` and after a stop token, so a
        speculative accepted run retires in one tick with the same
        stop semantics as token-by-token decode.  Latency histograms
        observe once per run (a run IS one model step).  Returns the
        number of tokens kept; with ``finish=False`` the caller
        retires the sequence itself after updating cache lengths."""
        kept = 0
        for j, tok in enumerate(toks):
            if len(seq.generated) >= seq.req.max_new:
                break
            tok = int(tok)
            seq.generated.append(tok)
            seq.last_token = tok
            if seq.req.return_logits:
                seq.logits.append(np.asarray(logits_rows[j]).copy())
            kept += 1
            if (seq.req.stop_token is not None
                    and tok == seq.req.stop_token):
                break
        if kept:
            telemetry.counter("serve_tokens_total").inc(kept)
            if seq.t_first is None:
                seq.t_first = now
                telemetry.histogram("serve_ttft_seconds").observe(
                    now - seq.req.t_submit)
            else:
                telemetry.histogram("serve_token_seconds").observe(
                    now - seq.t_last)
            seq.t_last = now
        if finish and seq.done:
            self._finish(seq)
        return kept

    def _finish(self, seq: _Seq) -> None:
        res = GenerationResult(
            np.asarray(seq.generated, np.int32),
            np.stack(seq.logits) if seq.logits else None,
            seq.req.tokens.size, seq.slot,
            seq.t_first - seq.req.t_submit)
        self._release(seq.slot)
        with self.stats.lock:
            self.stats.requests += 1
        dur = time.monotonic() - seq.req.t_submit
        with self._cond:
            self._req_ewma = (dur if self._req_ewma == 0.0
                              else 0.8 * self._req_ewma + 0.2 * dur)
        telemetry.counter("serve_requests_total").inc()
        telemetry.counter("serve_slot_recycles_total").inc()
        telemetry.histogram("serve_request_seconds").observe(dur)
        seq.req.future.set_result(res)

    # -------------------------------------------------------------- decode
    def _decode_step(self) -> None:
        """One token for every running slot — THE continuous batch."""
        tokens = np.zeros(self.max_slots, np.int32)
        active = []
        for i, seq in enumerate(self._seqs):
            if seq is not None:
                tokens[i] = seq.last_token
                active.append(seq)
        if not active:
            return
        with self._cond:
            self.active_high_water = max(self.active_high_water,
                                         len(active))
        telemetry.histogram("serve_decode_active").observe(len(active))
        logits = np.asarray(self._decode_batch(tokens))
        now = time.monotonic()
        for seq in active:
            # the decode wrote this token's K/V at position `length`
            seq.length += 1
            self._lengths[seq.slot] = seq.length
            if seq.req.trace is not None:
                # tick span runs from the previous phase boundary, so
                # batch-wait between ticks is attributed to the tick.
                # Recorded BEFORE _emit: a finishing sequence settles
                # (and finalizes its trace) inside _emit, which would
                # drop the final tick's span
                tracing.record(seq.req.trace, "serve.decode_tick",
                               seq.t_cursor, now)
                seq.t_cursor = now
            self._emit(seq, logits[seq.slot], now)
            # deadline: a running sequence past its deadline stops with
            # what it has rather than holding the slot
            if (self._seqs[seq.slot] is seq
                    and seq.req.deadline is not None
                    and now > seq.req.deadline):
                self._finish(seq)

    def _decode_batch(self, tokens: np.ndarray):
        """Run the one-decode program over the slot batch (hook: the
        paged engine gathers through its block tables instead)."""
        self._cache_k, self._cache_v, logits = self.model.decode(
            self._cache_k, self._cache_v, tokens, self._lengths)
        return logits
