"""Paged KV cache with prefix caching: block-pool memory management
for the serving engine.

The PR 5 engine (``generate.py``) gives every slot a max-length
rectangle, so concurrency is bounded by the WORST-CASE sequence length
rather than by actual HBM use.  This module replaces the rectangle with
the vLLM/PagedAttention block-table design (Kwon et al., 2023), adapted
to this repo's one-compiled-decode contract:

- **Block pool** — one device-resident pool of ``TP_SERVE_KV_POOL_BLOCKS``
  fixed-size pages of ``TP_SERVE_PAGE_TOKENS`` tokens each (+ one
  scratch page absorbing padded writes).  A sequence owns
  ``ceil((prompt + max_new) / page)`` pages instead of ``max_len``, so
  at equal HBM budget the pool admits strictly more concurrent
  mixed-length sequences than the rectangle.
- **Page tables** — each slot owns one row of a padded, fixed-shape
  ``(max_slots, max_pages)`` table; unowned entries point at the
  scratch page.  Decode gathers every slot's pages through the table
  into the SAME rectangular view the PR 5 decode attends over, so
  decode stays ONE compiled program and greedy tokens stay bit-exact
  (``tests/test_paged_kv.py``).
- **Prefix caching** — completed FULL prompt pages are content-
  addressed by a rolling token hash (page ``i``'s digest commits to
  pages ``0..i``).  A new prompt sharing a cached prefix takes
  references on those pages and prefills only its suffix — the shared
  blocks skip prefill entirely (``serve_prefix_hits_total``, TTFT).
  Refcount-0 cached pages park in an LRU and are reclaimed LRU-first
  when the free list runs dry; copy-on-write diverges a shared page
  before any write could reach it (by construction decode writes
  always land past the shared prefix, so CoW is a defended invariant,
  not a hot path).
- **Admission by free pages** — :class:`PagedGenerationEngine` admits a
  request only when slot AND page budget are reservable up front
  (worst case, so decode can never deadlock on allocation mid-flight);
  expired or failed requests release their reservation before the
  future resolves.

Telemetry: ``serve_kv_pages_free`` / ``serve_kv_pages_used`` /
``serve_kv_pages_cached`` / ``serve_kv_pool_bytes`` gauges,
``serve_prefix_hits_total`` / ``serve_prefix_hit_tokens_total`` /
``serve_prefix_evictions_total`` / ``serve_kv_cow_total`` counters.
See docs/paged_kv.md for the block math and eviction policy.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry, tracing
from ..base import MXNetError, get_env
from .engine import bucket_batch, bucket_length
from .generate import GenerationEngine, KVTransformerLM, _GenPending, \
    _ln, _Seq

__all__ = ["BlockPool", "PagedKVCache", "PagedGenerationEngine",
           "prefix_hashes"]

_HASH_SEED = b"tp-paged-prefix-v1"


def prefix_hashes(tokens, page_tokens: int) -> List[bytes]:
    """Rolling content hash per FULL page of ``tokens``: page ``i``'s
    digest commits to every token of pages ``0..i``, so equal digests
    mean equal whole prefixes, and a chain walk stops at the first
    divergent page."""
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
    out: List[bytes] = []
    h = _HASH_SEED
    for i in range(toks.size // page_tokens):
        page = toks[i * page_tokens:(i + 1) * page_tokens]
        h = hashlib.blake2b(h + page.tobytes(), digest_size=16).digest()
        out.append(h)
    return out


class PoolStats:
    """Host-side mirror of the pool telemetry (always on, so tests and
    benches read it without enabling the global registry).  Mutated
    only under the pool lock."""

    __slots__ = ("prefix_hits", "prefix_hit_tokens", "prefix_misses",
                 "evictions", "cow_copies", "allocs", "frees")

    def __init__(self):
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.prefix_misses = 0
        self.evictions = 0
        self.cow_copies = 0
        self.allocs = 0
        self.frees = 0


class BlockPool:
    """Refcounted allocator over a fixed set of KV pages.

    Thread-safe: one lock, every method a short critical section that
    never calls out while holding it.  A block is always in exactly one
    of three states:

    - **free** — on the free list (refcount 0, no hash);
    - **live** — refcount ≥ 1, owned by one or more slots (a shared
      prefix block is live with refcount = number of sharers);
    - **cached** — refcount 0 but still content-addressed: a future
      prompt can revive it by hash (:meth:`share`), and :meth:`alloc`
      reclaims cached blocks LRU-first when the free list runs dry.
    """

    def __init__(self, num_blocks: int, page_tokens: int):
        if num_blocks < 1:
            raise MXNetError("BlockPool needs >= 1 block, got %d"
                             % num_blocks)
        self.num_blocks = int(num_blocks)
        self.page_tokens = int(page_tokens)
        self.lock = threading.Lock()
        self.stats = PoolStats()
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._ref = np.zeros(num_blocks, np.int64)
        self._hash_of: Dict[int, bytes] = {}
        self._block_of: Dict[bytes, int] = {}
        # insertion order = LRU order of cached (refcount-0) blocks
        self._lru: Dict[int, None] = {}
        with self.lock:
            self._gauges()

    # ------------------------------------------------------------ accounting
    def _gauges(self) -> None:
        """Refresh the pool occupancy gauges (call under the lock)."""
        telemetry.gauge("serve_kv_pages_free").set(len(self._free))
        telemetry.gauge("serve_kv_pages_cached").set(len(self._lru))
        telemetry.gauge("serve_kv_pages_used").set(
            self.num_blocks - len(self._free) - len(self._lru))

    def available(self) -> int:
        """Pages an :meth:`alloc` could deliver right now (free +
        cached-evictable)."""
        with self.lock:
            return len(self._free) + len(self._lru)

    def free_blocks(self) -> int:
        with self.lock:
            return len(self._free)

    def cached_blocks(self) -> int:
        with self.lock:
            return len(self._lru)

    def used_blocks(self) -> int:
        with self.lock:
            return self.num_blocks - len(self._free) - len(self._lru)

    def refcount(self, blk: int) -> int:
        with self.lock:
            return int(self._ref[blk])

    def snapshot(self) -> Dict[str, object]:
        """One consistent under-lock snapshot of pool occupancy plus
        the registered prefix digests — the paged half of the fleet
        router's ``load_report()`` probe.  Separate ``free_blocks()`` /
        ``cached_blocks()`` calls could interleave with an alloc and
        report pages that sum to more than the pool; the probe contract
        is one critical section per report."""
        with self.lock:
            free = len(self._free)
            cached = len(self._lru)
            return {
                "free": free,
                "cached": cached,
                "used": self.num_blocks - free - cached,
                "digests": frozenset(self._block_of),
                "prefix_hits": self.stats.prefix_hits,
                "prefix_hit_tokens": self.stats.prefix_hit_tokens,
                "evictions": self.stats.evictions,
            }

    # ------------------------------------------------------------- lifecycle
    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` fresh private blocks (refcount 1, unhashed),
        evicting cached prefix blocks LRU-first when the free list runs
        dry.  Returns None — and allocates nothing — when even eviction
        cannot cover the request (the caller defers admission)."""
        with self.lock:
            if n > len(self._free) + len(self._lru):
                return None
            evicted = 0
            while len(self._free) < n:
                blk = next(iter(self._lru))  # oldest cached block
                del self._lru[blk]
                del self._block_of[self._hash_of.pop(blk)]
                self._free.append(blk)
                evicted += 1
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
            self.stats.allocs += n
            self.stats.evictions += evicted
            self._gauges()
        if evicted:
            telemetry.counter("serve_prefix_evictions_total").inc(evicted)
        return out

    def share(self, digest: bytes) -> Optional[int]:
        """Look up a prefix block by content hash; on a hit, take a
        reference (reviving a cached block from the LRU)."""
        with self.lock:
            blk = self._block_of.get(digest)
            if blk is None:
                self.stats.prefix_misses += 1
                return None
            self._ref[blk] += 1
            self._lru.pop(blk, None)
            self.stats.prefix_hits += 1
            self.stats.prefix_hit_tokens += self.page_tokens
            self._gauges()
        telemetry.counter("serve_prefix_hits_total").inc()
        telemetry.counter("serve_prefix_hit_tokens_total").inc(
            self.page_tokens)
        return blk

    def register(self, blk: int, digest: bytes) -> None:
        """Content-address a live block (a completed FULL prefill
        page).  First writer wins: if the digest is already mapped (two
        identical prompts prefilled in one batch), the later block just
        stays private."""
        with self.lock:
            if self._ref[blk] <= 0:
                raise MXNetError(
                    "register of non-live KV page %d" % blk)
            if digest in self._block_of or blk in self._hash_of:
                return
            self._block_of[digest] = blk
            self._hash_of[blk] = digest

    def release(self, blocks) -> None:
        """Drop one reference per block.  At refcount 0 a hashed block
        parks in the LRU (cached — still shareable, reclaimable);
        an unhashed block returns to the free list.  Releasing a
        refcount-0 block (double free) raises."""
        with self.lock:
            for blk in blocks:
                if self._ref[blk] <= 0:
                    raise MXNetError(
                        "double free of KV page %d (refcount already 0)"
                        % blk)
                self._ref[blk] -= 1
                if self._ref[blk] == 0:
                    if blk in self._hash_of:
                        self._lru[blk] = None  # most-recently released
                    else:
                        self._free.append(blk)
                self.stats.frees += 1
            self._gauges()

    def make_private(self, blk: int) -> Tuple[int, bool]:
        """Copy-on-write bookkeeping: return a block the caller may
        write.  A refcount-1 unhashed block comes back as-is; a
        refcount-1 hashed block is un-registered (exclusive owner —
        cheaper than copying); a shared block is swapped for a fresh
        one with the old reference dropped, and the caller must copy
        the page contents on device.  Returns ``(block, needs_copy)``.
        """
        with self.lock:
            if self._ref[blk] <= 0:
                raise MXNetError(
                    "make_private of non-live KV page %d" % blk)
            if self._ref[blk] == 1:
                h = self._hash_of.pop(blk, None)
                if h is not None:
                    del self._block_of[h]
                return blk, False
        fresh = self.alloc(1)
        if fresh is None:
            raise MXNetError("KV page pool exhausted during copy-on-"
                             "write divergence")
        self.release([blk])
        with self.lock:
            self.stats.cow_copies += 1
        telemetry.counter("serve_kv_cow_total").inc()
        return fresh[0], True


class PagedKVCache:
    """Device-resident paged KV store for a :class:`KVTransformerLM`.

    The cache is a pair of ``(num_blocks + 1, layers, heads,
    page_tokens, head_dim)`` arrays — block-major, with the scratch
    block at index ``num_blocks`` absorbing padded writes (the paged
    analog of the rectangular engine's scratch slot).  Each slot owns a
    row of the host-side ``(max_slots, max_pages)`` page table; token
    page ``p`` of a slot (positions ``[p*P, (p+1)*P)``) lives in pool
    block ``tables[slot, p]``.

    Compiled programs (keys recorded in ``model.stats``):

    - ``("paged_prefill", N, L)`` per (batch-bucket, suffix-length-
      bucket): runs only the prompt SUFFIX past the shared prefix;
      attention over gathered prefix pages + causal self-attention in
      one softmax, K/V scattered whole-page through a write table.
    - ``("paged_decode", slots)`` — ONE program ever: gathers every
      slot's pages into the same rectangular ``(slots, layers, heads,
      max_pages*P, head_dim)`` view the PR 5 decode attends over, and
      scatters the new token's K/V at ``(tables[slot, len//P],
      len % P)``.
    """

    def __init__(self, model: KVTransformerLM, max_slots: int,
                 max_len: int, *, page_tokens: Optional[int] = None,
                 num_blocks: Optional[int] = None):
        import jax.numpy as jnp

        from ..base import dtype_np

        s = model.spec
        if max_len > s.max_seq:
            raise MXNetError(
                "max_len %d exceeds the model's position table (%d)"
                % (max_len, s.max_seq))
        self.model = model
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.page_tokens = int(
            page_tokens if page_tokens is not None
            else get_env("SERVE_PAGE_TOKENS", 16, int))
        if self.page_tokens < 1:
            raise MXNetError("page_tokens must be >= 1")
        P = self.page_tokens
        self.max_pages = -(-self.max_len // P)
        if num_blocks is None:
            num_blocks = get_env("SERVE_KV_POOL_BLOCKS", 0, int) \
                or self.max_slots * self.max_pages
        self.num_blocks = int(num_blocks)
        self.scratch = self.num_blocks
        self.pool = BlockPool(self.num_blocks, P)
        dt = dtype_np(model.kv_dtype)
        shape = (self.num_blocks + 1, s.num_layers, s.heads, P,
                 s.head_dim)
        self.cache_k = jnp.zeros(shape, dt)
        self.cache_v = jnp.zeros(shape, dt)
        telemetry.gauge("serve_kv_pool_bytes").set(
            2 * int(np.prod(shape)) * np.dtype(dt).itemsize)
        self.tables = np.full((self.max_slots, self.max_pages),
                              self.scratch, np.int32)
        self._owned: Dict[int, List[int]] = {}
        self._shared_n: Dict[int, int] = {}
        self._prefill_fns = {}
        self._decode_fn = None
        self._verify_fns = {}

    # --------------------------------------------------------- slot lifecycle
    def pages_needed(self, prompt_len: int, max_new: int,
                     extra: int = 0) -> int:
        """Worst-case page budget of one request: every position the
        sequence can ever write, rounded up to whole pages.  ``extra``
        covers positions written only transiently — the speculative
        verify pass scatters k candidate K/V rows past the accepted
        length, and reserving them up front is what makes rollback
        free (no mid-speculation allocation, so no mid-speculation
        failure and no page leak)."""
        return -(-(int(prompt_len) + int(max_new) + int(extra))
                 // self.page_tokens)

    def try_admit(self, slot: int, tokens, max_new: int,
                  extra: int = 0) -> Optional[int]:
        """Reserve the request's whole worst-case page budget on slot
        ``slot``, reusing cached prefix pages by content hash.  Returns
        the shared-prefix token count, or None (reserving nothing) when
        the pool cannot cover the request right now — the caller keeps
        it queued and retries after frees.  Reserving up front means
        decode can never stall on allocation mid-flight."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        P = self.page_tokens
        total = self.pages_needed(toks.size, max_new, extra)
        if total > self.max_pages:
            raise MXNetError(
                "request needs %d pages > max_pages %d"
                % (total, self.max_pages))
        # only FULL pages strictly before the last prompt token are
        # shareable: prefill must still run >= 1 suffix token to emit
        # the first-token (TTFT) logits
        shared: List[int] = []
        for d in prefix_hashes(toks, P)[:(toks.size - 1) // P]:
            blk = self.pool.share(d)
            if blk is None:
                break
            shared.append(blk)
        fresh = self.pool.alloc(total - len(shared))
        if fresh is None:
            self.pool.release(shared)  # roll the reservation back
            return None
        row = self.tables[slot]
        row[:] = self.scratch
        blocks = shared + fresh
        row[:total] = blocks
        self._owned[slot] = blocks
        self._shared_n[slot] = len(shared)
        return len(shared) * P

    def release_slot(self, slot: int) -> None:
        """Return every page the slot owns (one refcount each: shared
        prefix pages stay alive for their other sharers; private pages
        free; hashed refcount-0 pages park in the prefix LRU) and reset
        the slot's table row to scratch."""
        blocks = self._owned.pop(slot, None)
        self._shared_n.pop(slot, None)
        self.tables[slot, :] = self.scratch
        if blocks:
            self.pool.release(blocks)

    def register_prompt(self, slot: int, tokens,
                        upto: Optional[int] = None) -> None:
        """Content-address the slot's freshly prefilled FULL prompt
        pages (past any shared prefix) so later prompts can skip them.
        Call only after the prefill that filled them has been issued.
        ``upto`` limits registration to the first ``upto`` tokens —
        chunked prefill registers chunk-at-a-time as pages complete
        (``register`` is idempotent, so re-registering is safe)."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        if upto is not None:
            toks = toks[:int(upto)]
        digests = prefix_hashes(toks, self.page_tokens)
        row = self.tables[slot]
        for g in range(self._shared_n.get(slot, 0), len(digests)):
            self.pool.register(int(row[g]), digests[g])

    def shared_pages(self, slot: int) -> int:
        return self._shared_n.get(slot, 0)

    def owned_pages(self, slot: int) -> int:
        return len(self._owned.get(slot, ()))

    def ensure_writable(self, slot: int, position: int) -> None:
        """Copy-on-write guard: make the page holding ``position``
        privately owned before a write.  Decode writes land past the
        shared prefix by construction, so this never copies on the hot
        path — but if a shared page were ever the write target, it
        diverges here instead of corrupting the cached prefix."""
        page = int(position) // self.page_tokens
        blk = int(self.tables[slot, page])
        if blk == self.scratch:
            return
        new, copied = self.pool.make_private(blk)
        if copied:
            self.cache_k = self.cache_k.at[new].set(self.cache_k[blk])
            self.cache_v = self.cache_v.at[new].set(self.cache_v[blk])
        if new != blk:
            self.tables[slot, page] = new
            owned = self._owned[slot]
            owned[owned.index(blk)] = new
            if page < self._shared_n.get(slot, 0):
                self._shared_n[slot] = page

    # ------------------------------------------------------------ programs
    def _build_prefill(self, L: int):
        import jax
        import jax.numpy as jnp

        model = self.model
        s = model.spec
        P = self.page_tokens
        Lp = -(-L // P)
        S = self.max_pages * P
        scale = 1.0 / s.head_dim ** 0.5
        neg = jnp.finfo(jnp.float32).min

        def prefill(cache_k, cache_v, tokens, prefix_lens, suffix_lens,
                    tables, write_tables):
            # tokens (N, L): prompt SUFFIX past the shared prefix;
            # prefix_lens/suffix_lens (N,); tables (N, max_pages);
            # write_tables (N, Lp) — the fresh blocks the suffix pages
            # scatter into (scratch for padding)
            N = tokens.shape[0]
            positions = prefix_lens[:, None] + jnp.arange(L)[None, :]
            x = model._embed(tokens,
                             jnp.minimum(positions, s.max_seq - 1))
            causal = (jnp.arange(L)[:, None] >= jnp.arange(L)[None, :])
            # cached-page mask: gathered position j is real prefix iff
            # j < prefix_len (shared pages hold exactly prefix_len
            # tokens; everything else in the gather is masked garbage)
            pmask = (jnp.arange(S)[None, :]
                     < prefix_lens[:, None])[:, None, None, :]
            gk = cache_k[tables]  # (N, max_pages, layers, H, P, D)
            gv = cache_v[tables]
            gk = jnp.reshape(jnp.moveaxis(gk, 1, 3),
                             (N, s.num_layers, s.heads, S, s.head_dim))
            gv = jnp.reshape(jnp.moveaxis(gv, 1, 3),
                             (N, s.num_layers, s.heads, S, s.head_dim))
            ks, vs = [], []
            for i in range(s.num_layers):
                h = _ln(x, model.params["block%d_ln1_gamma" % i],
                        model.params["block%d_ln1_beta" % i])
                q, k, v = model._qkv(i, h)      # (N, L, H, D)
                q = jnp.moveaxis(q, 1, 2)       # (N, H, L, D)
                k = jnp.moveaxis(k, 1, 2)
                v = jnp.moveaxis(v, 1, 2)
                kc = gk[:, i].astype(jnp.float32)
                vc = gv[:, i].astype(jnp.float32)
                spre = jnp.einsum("nhqd,nhkd->nhqk", q, kc) * scale
                spre = jnp.where(pmask, spre, neg)
                sself = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
                sself = jnp.where(causal, sself, neg)
                # one softmax over [cached prefix | causal suffix]:
                # masked lanes underflow to exactly 0, so a fresh
                # prompt (prefix 0) matches the rectangular prefill
                # bit-for-bit
                w = jax.nn.softmax(
                    jnp.concatenate([spre, sself], axis=-1), axis=-1)
                att = jnp.einsum("nhqk,nhkd->nhqd", w[..., :S], vc) \
                    + jnp.einsum("nhqk,nhkd->nhqd", w[..., S:], v)
                att = jnp.moveaxis(att, 1, 2)   # (N, L, H, D)
                x = model._attn_out(i, att, x)
                x = model._ffn(i, x)
                ks.append(k)
                vs.append(v)
            knew = jnp.stack(ks, axis=1)        # (N, layers, H, L, D)
            vnew = jnp.stack(vs, axis=1)
            pad = Lp * P - L
            if pad:
                cfg = ((0, 0), (0, 0), (0, 0), (0, pad), (0, 0))
                knew = jnp.pad(knew, cfg)
                vnew = jnp.pad(vnew, cfg)
            # whole-page scatter: (N, Lp, layers, H, P, D) rows land on
            # the write table's blocks.  Tail positions past the real
            # suffix hold garbage but sit beyond `length`, so they are
            # never attended — then decode overwrites them token by
            # token (same contract as the rectangular padded rows).
            knew = jnp.moveaxis(jnp.reshape(
                knew, (N, s.num_layers, s.heads, Lp, P, s.head_dim)),
                3, 1)
            vnew = jnp.moveaxis(jnp.reshape(
                vnew, (N, s.num_layers, s.heads, Lp, P, s.head_dim)),
                3, 1)
            cache_k = cache_k.at[write_tables].set(
                knew.astype(cache_k.dtype))
            cache_v = cache_v.at[write_tables].set(
                vnew.astype(cache_v.dtype))
            x = _ln(x, model.params["ln_f_gamma"],
                    model.params["ln_f_beta"])
            last = jnp.take_along_axis(
                x, (suffix_lens - 1)[:, None, None], axis=1)[:, 0]
            return cache_k, cache_v, model._head(last)

        return prefill

    def prefill(self, tokens: np.ndarray, prefix_lens: np.ndarray,
                suffix_lens: np.ndarray, slots: np.ndarray):
        """Run one padded suffix bucket through the paged prefill.
        ``tokens`` (N, L) holds each request's suffix; ``slots`` (N,)
        maps rows to slots, -1 for padding rows (scratch everywhere).
        Mutates the cache in place; returns last-position logits."""
        import jax
        import jax.numpy as jnp

        N, L = tokens.shape
        P = self.page_tokens
        Lp = -(-L // P)
        fn = self._prefill_fns.get((N, L))
        if fn is None:
            fn = jax.jit(self._build_prefill(L))
            self._prefill_fns[(N, L)] = fn
        self.model.stats.record_batch(
            ("paged_prefill", N, L),
            int((np.asarray(slots) >= 0).sum()), N, "prefill")
        tables = np.full((N, self.max_pages), self.scratch, np.int32)
        write = np.full((N, Lp), self.scratch, np.int32)
        for j in range(N):
            if slots[j] < 0:
                continue
            row = self.tables[slots[j]]
            tables[j] = row
            start = int(prefix_lens[j]) // P
            for p in range(Lp):
                if start + p < self.max_pages:
                    write[j, p] = row[start + p]
        self.cache_k, self.cache_v, logits = fn(
            self.cache_k, self.cache_v,
            jnp.array(tokens, jnp.int32),
            jnp.array(prefix_lens, jnp.int32),
            jnp.array(suffix_lens, jnp.int32),
            jnp.array(tables, jnp.int32),
            jnp.array(write, jnp.int32))
        return logits

    def _build_decode(self):
        import jax
        import jax.numpy as jnp

        model = self.model
        s = model.spec
        P = self.page_tokens
        S = self.max_pages * P
        scale = 1.0 / s.head_dim ** 0.5
        neg = jnp.finfo(jnp.float32).min

        def decode(cache_k, cache_v, tokens, lengths, tables):
            # tokens/lengths (slots,) int32; tables (slots, max_pages).
            # The gather materializes the SAME rectangular view the
            # PR 5 decode attends over — position j of the view is
            # token position j of the sequence — so the attention math
            # (and its reduction shapes) are identical and greedy
            # tokens are bit-exact.
            nslots = tokens.shape[0]
            x = model._embed(tokens, lengths)
            mask = (jnp.arange(S)[None, :]
                    < lengths[:, None])[:, None, :]
            gk = jnp.reshape(jnp.moveaxis(cache_k[tables], 1, 3),
                             (nslots, s.num_layers, s.heads, S,
                              s.head_dim))
            gv = jnp.reshape(jnp.moveaxis(cache_v[tables], 1, 3),
                             (nslots, s.num_layers, s.heads, S,
                              s.head_dim))
            ks, vs = [], []
            for i in range(s.num_layers):
                h = _ln(x, model.params["block%d_ln1_gamma" % i],
                        model.params["block%d_ln1_beta" % i])
                q, k, v = model._qkv(i, h)      # (slots, H, D)
                kc = gk[:, i].astype(jnp.float32)
                vc = gv[:, i].astype(jnp.float32)
                sc = jnp.einsum("nhd,nhkd->nhk", q, kc) * scale
                sc = jnp.where(mask, sc, neg)
                s_self = jnp.einsum("nhd,nhd->nh", q, k) * scale
                full = jnp.concatenate([sc, s_self[..., None]],
                                       axis=-1)
                w = jax.nn.softmax(full, axis=-1)
                att = jnp.einsum("nhk,nhkd->nhd", w[..., :S], vc) \
                    + w[..., S, None] * v
                x = model._attn_out(i, att, x)
                x = model._ffn(i, x)
                ks.append(k)
                vs.append(v)
            knew = jnp.stack(ks, axis=1)    # (slots, layers, H, D)
            vnew = jnp.stack(vs, axis=1)
            pos = jnp.minimum(lengths, S - 1)
            blk = jnp.take_along_axis(tables, (pos // P)[:, None],
                                      axis=1)[:, 0]
            off = pos % P
            cache_k = cache_k.at[blk, :, :, off, :].set(
                knew.astype(cache_k.dtype))
            cache_v = cache_v.at[blk, :, :, off, :].set(
                vnew.astype(cache_v.dtype))
            x = _ln(x, model.params["ln_f_gamma"],
                    model.params["ln_f_beta"])
            return cache_k, cache_v, model._head(x)

        return decode

    def decode(self, tokens: np.ndarray, lengths: np.ndarray):
        """One single-token step over the full slot batch — the ONE
        compiled paged-decode program.  Mutates the cache in place;
        returns (slots, vocab) logits."""
        import jax
        import jax.numpy as jnp

        if self._decode_fn is None:
            self._decode_fn = jax.jit(self._build_decode())
        n = int(np.asarray(tokens).shape[0])
        self.model.stats.record_batch(("paged_decode", n), n, n,
                                      "decode")
        self.cache_k, self.cache_v, logits = self._decode_fn(
            self.cache_k, self.cache_v,
            jnp.array(tokens, jnp.int32),
            jnp.array(lengths, jnp.int32),
            jnp.array(self.tables, jnp.int32))
        return logits

    def _build_verify(self, M: int):
        import jax
        import jax.numpy as jnp

        model = self.model
        s = model.spec
        P = self.page_tokens
        S = self.max_pages * P
        scale = 1.0 / s.head_dim ** 0.5
        neg = jnp.finfo(jnp.float32).min

        def verify(cache_k, cache_v, tokens, lengths, tables):
            # tokens (slots, M): M candidate continuations per slot at
            # positions `lengths .. lengths+M-1`; same gathered
            # rectangular view as paged decode, causal among the M,
            # ONE softmax over [cached | candidates] so greedy rows
            # match sequential paged decode bit-for-bit (the masked-
            # lanes-underflow-to-0 argument of the suffix prefill)
            nslots = tokens.shape[0]
            positions = lengths[:, None] + jnp.arange(M)[None, :]
            x = model._embed(tokens,
                             jnp.minimum(positions, s.max_seq - 1))
            cmask = (jnp.arange(S)[None, :]
                     < lengths[:, None])[:, None, None, :]
            causal = (jnp.arange(M)[:, None]
                      >= jnp.arange(M)[None, :])
            gk = jnp.reshape(jnp.moveaxis(cache_k[tables], 1, 3),
                             (nslots, s.num_layers, s.heads, S,
                              s.head_dim))
            gv = jnp.reshape(jnp.moveaxis(cache_v[tables], 1, 3),
                             (nslots, s.num_layers, s.heads, S,
                              s.head_dim))
            ks, vs = [], []
            for i in range(s.num_layers):
                h = _ln(x, model.params["block%d_ln1_gamma" % i],
                        model.params["block%d_ln1_beta" % i])
                q, k, v = model._qkv(i, h)       # (slots, M, H, D)
                qh = jnp.moveaxis(q, 1, 2)       # (slots, H, M, D)
                kh = jnp.moveaxis(k, 1, 2)
                vh = jnp.moveaxis(v, 1, 2)
                kc = gk[:, i].astype(jnp.float32)
                vc = gv[:, i].astype(jnp.float32)
                spre = jnp.einsum("nhqd,nhkd->nhqk", qh, kc) * scale
                spre = jnp.where(cmask, spre, neg)
                sself = jnp.einsum("nhqd,nhkd->nhqk", qh, kh) * scale
                sself = jnp.where(causal, sself, neg)
                w = jax.nn.softmax(
                    jnp.concatenate([spre, sself], axis=-1), axis=-1)
                att = jnp.einsum("nhqk,nhkd->nhqd", w[..., :S], vc) \
                    + jnp.einsum("nhqk,nhkd->nhqd", w[..., S:], vh)
                att = jnp.moveaxis(att, 1, 2)    # (slots, M, H, D)
                x = model._attn_out(i, att, x)
                x = model._ffn(i, x)
                ks.append(k)
                vs.append(v)
            # scatter all M candidate rows through the page table —
            # rejected positions sit past `length` afterwards, so they
            # are unreachable (mask) and overwritten by later writes;
            # the reservation's `extra` headroom guarantees the target
            # pages are owned, so no page of another slot is touched
            knew = jnp.stack(ks, axis=2)     # (slots, M, layers, H, D)
            vnew = jnp.stack(vs, axis=2)
            pos = jnp.minimum(positions, S - 1)          # (slots, M)
            blk = jnp.take_along_axis(tables, pos // P, axis=1)
            off = pos % P
            cache_k = cache_k.at[blk, :, :, off, :].set(
                knew.astype(cache_k.dtype))
            cache_v = cache_v.at[blk, :, :, off, :].set(
                vnew.astype(cache_v.dtype))
            x = _ln(x, model.params["ln_f_gamma"],
                    model.params["ln_f_beta"])
            return cache_k, cache_v, model._head(x)

        return verify

    def verify(self, tokens: np.ndarray, lengths: np.ndarray,
               active: Optional[np.ndarray] = None):
        """Score M candidate positions per slot in ONE compiled pass
        (the paged speculative verify).  ``tokens`` (slots, M);
        ``active`` masks rows whose slots should not be written (their
        gather/scatter pages are redirected to scratch — a slot mid-
        chunked-prefill must not have candidate garbage scattered into
        pages its next chunk will fill).  Mutates the cache in place;
        returns (slots, M, vocab) logits."""
        import jax
        import jax.numpy as jnp

        n, M = np.asarray(tokens).shape
        fn = self._verify_fns.get((n, M))
        if fn is None:
            fn = jax.jit(self._build_verify(M))
            self._verify_fns[(n, M)] = fn
        nact = n if active is None else int(np.asarray(active).sum())
        self.model.stats.record_batch(("paged_verify", n, M), nact, n,
                                      "verify")
        tables = self.tables
        if active is not None:
            tables = np.where(np.asarray(active, bool)[:, None],
                              self.tables, np.int32(self.scratch))
        self.cache_k, self.cache_v, logits = fn(
            self.cache_k, self.cache_v,
            jnp.array(tokens, jnp.int32),
            jnp.array(lengths, jnp.int32),
            jnp.array(tables, jnp.int32))
        return logits


class PagedGenerationEngine(GenerationEngine):
    """:class:`GenerationEngine` over a :class:`PagedKVCache`: same
    continuous-batching loop, but admission reserves KV PAGES (worst
    case per request) instead of a max-length rectangle, prompts
    sharing a cached prefix prefill only their suffix, and finished or
    expired sequences return their pages to the pool.

    Extra knobs: ``page_tokens`` (``TP_SERVE_PAGE_TOKENS``, default 16)
    and ``pool_blocks`` (``TP_SERVE_KV_POOL_BLOCKS``, default
    ``max_slots * ceil(max_len / page_tokens)`` — the same HBM as the
    rectangle, which the pool then shares by actual need)."""

    def __init__(self, model: KVTransformerLM, *,
                 page_tokens: Optional[int] = None,
                 pool_blocks: Optional[int] = None, **kw):
        self._ctor_page_tokens = page_tokens
        self._ctor_pool_blocks = pool_blocks
        kw.setdefault("name", "serve_paged_lm")
        super().__init__(model, **kw)

    def _setup_cache(self) -> None:
        self._kv = PagedKVCache(
            self.model, self.max_slots, self.max_len,
            page_tokens=self._ctor_page_tokens,
            num_blocks=self._ctor_pool_blocks)
        # the paged cache owns the device arrays; the rectangular
        # attrs stay unused
        self._cache_k = self._cache_v = None

    @property
    def pool(self) -> BlockPool:
        return self._kv.pool

    @property
    def kv(self) -> PagedKVCache:
        return self._kv

    # ---------------------------------------------------------- admission
    def _check_request(self, tokens: np.ndarray, max_new: int) -> None:
        super()._check_request(tokens, max_new)
        need = self._kv.pages_needed(tokens.size, max_new,
                                     self._spec_reserve_extra())
        if need > self._kv.num_blocks:
            raise MXNetError(
                "request needs %d KV pages but the pool holds only %d "
                "(TP_SERVE_KV_POOL_BLOCKS)"
                % (need, self._kv.num_blocks))

    def _take_admissible(self) -> List[_GenPending]:
        """Admit by free-PAGE count: reserve each request's worst-case
        page budget (and a slot) up front; the first request that does
        not fit blocks the queue (FIFO — no starvation) until frees
        make room.  Must hold the lock."""
        free = [i for i, s in enumerate(self._seqs) if s is None]
        take: List[_GenPending] = []
        rest: List[_GenPending] = []
        for p in self._pending:
            if rest or not free:
                rest.append(p)
                continue
            t_a0 = time.monotonic() if p.trace is not None else 0.0
            shared = self._kv.try_admit(free[0], p.tokens, p.max_new,
                                        extra=self._spec_reserve_extra())
            if shared is None:
                rest.append(p)
                continue
            if p.trace is not None:
                # reservation cost: overlaps the queue phase, so
                # trace_query treats it as attribution detail, not a
                # critical-path phase
                tracing.record(p.trace, "serve.page_alloc", t_a0,
                               time.monotonic(),
                               {"shared_tokens": int(shared)})
            p.slot = free.pop(0)
            p.shared_tokens = shared
            take.append(p)
        self._pending = rest
        telemetry.gauge("serve_queue_depth").set(len(self._pending))
        return take

    def _admit(self, reqs: List[_GenPending]) -> None:
        """Prefill each newcomer's SUFFIX past its shared prefix,
        bucketed by suffix length; register the fresh full prompt
        pages for future prefix hits; sample the first token (TTFT).
        A request whose deadline expired between reservation and here
        releases its pages BEFORE its future fails."""
        now = time.monotonic()
        live: List[_GenPending] = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                self._abort_admission(r)
                self.stats.expired += 1
                telemetry.counter("serve_deadline_expired_total").inc()
                if r.trace is not None:
                    tracing.flag(r.trace, "deadline")
                    tracing.record(r.trace, "serve.queue",
                                   r.t_submit, now)
                r.future.set_exception(MXNetError(
                    "request deadline expired after %.1f ms in queue"
                    % ((now - r.t_submit) * 1e3)))
            else:
                live.append(r)
        groups: Dict[int, List[_GenPending]] = {}
        for r in live:
            L = bucket_length(r.tokens.size - r.shared_tokens,
                              self.max_len)
            groups.setdefault(L, []).append(r)
        for L, group in sorted(groups.items()):
            for start in range(0, len(group), self.max_slots):
                chunk = group[start:start + self.max_slots]
                n = len(chunk)
                nb = bucket_batch(n, self.max_slots)
                toks = np.zeros((nb, L), np.int32)
                plens = np.zeros(nb, np.int32)
                slens = np.ones(nb, np.int32)
                slots = np.full(nb, -1, np.int32)
                for j, r in enumerate(chunk):
                    suffix = r.tokens[r.shared_tokens:]
                    toks[j, :suffix.size] = suffix
                    plens[j] = r.shared_tokens
                    slens[j] = suffix.size
                    slots[j] = r.slot
                    self.prefill_tokens += int(suffix.size)
                telemetry.counter("serve_prefill_tokens_total").inc(
                    int(sum(r.tokens.size - r.shared_tokens
                            for r in chunk)))
                t_p0 = time.monotonic()
                logits = np.asarray(
                    self._kv.prefill(toks, plens, slens, slots))
                now = time.monotonic()
                for j, r in enumerate(chunk):
                    seq = _Seq(r, r.slot, r.tokens.size)
                    self._seqs[r.slot] = seq
                    self._lengths[r.slot] = r.tokens.size
                    if r.trace is not None:
                        tracing.record(r.trace, "serve.queue",
                                       r.t_submit, t_p0)
                        tracing.record(
                            r.trace, "serve.prefill", t_p0, now,
                            {"tokens": int(r.tokens.size
                                           - r.shared_tokens),
                             "shared_tokens": int(r.shared_tokens),
                             "bucket": int(L)})
                        seq.t_cursor = now
                    # register before _emit: a 1-token request finishes
                    # inside _emit and releases the slot immediately —
                    # its prompt pages must already be content-
                    # addressed so they park in the LRU, not the free
                    # list
                    self._kv.register_prompt(r.slot, r.tokens)
                    self._emit(seq, logits[j], now)

    def _abort_admission(self, req: _GenPending) -> None:
        """Return the pages :meth:`PagedKVCache.try_admit` reserved for
        a request that will never be seated."""
        if req.slot is not None:
            self._kv.release_slot(req.slot)

    # --------------------------------------------------------------- probe
    def load_report(self) -> Dict[str, object]:
        """The rectangular probe plus real page occupancy and the
        pool's registered prefix digests (the fleet router's placement
        key).  ``free_pages`` counts allocatable pages — free plus
        cached-evictable, what :meth:`BlockPool.alloc` could actually
        deliver — from ONE pool critical section
        (:meth:`BlockPool.snapshot`)."""
        report = super().load_report()
        snap = self._kv.pool.snapshot()
        report.update(
            page_tokens=self._kv.page_tokens,
            free_pages=int(snap["free"]) + int(snap["cached"]),
            cached_pages=int(snap["cached"]),
            total_pages=self._kv.num_blocks,
            prefix_digests=snap["digests"],
            prefix_hits=int(snap["prefix_hits"]),
        )
        return report

    # ------------------------------------------------------------- decode
    def _decode_batch(self, tokens: np.ndarray) -> np.ndarray:
        P = self._kv.page_tokens
        for i, seq in enumerate(self._seqs):
            # CoW guard: only consult the pool when the write position
            # could touch a shared page (never true by construction —
            # shared pages end before the first decode write — but a
            # page copy beats silent prefix corruption)
            if seq is not None \
                    and seq.length // P < self._kv.shared_pages(i):
                self._kv.ensure_writable(i, seq.length)
        return self._kv.decode(tokens, self._lengths)

    # ------------------------------------------------------------ teardown
    def _release(self, slot: int) -> None:
        self._kv.release_slot(slot)
        super()._release(slot)
