"""Speculative decoding + chunked prefill: compute-side decode latency
for the serving engine.

Paged KV (``paged.py``) solved serving *memory*; this module attacks
the *compute* side of decode latency with two composable mechanisms
that bolt onto the existing continuous-batching loop:

- **Speculative decoding** (Leviathan et al., 2023).  A small
  :class:`DraftModel` proposes ``k`` greedy tokens per slot per tick
  (k+1 launches of ITS one-compiled-decode, nearly free next to the
  target), then the target scores all ``k+1`` candidate positions in
  ONE compiled ``verify`` pass — the prefill attention math over the
  cached prefix plus a causal block among the candidates, one softmax
  per row, so each verify row equals the sequential decode step
  bit-for-bit.  The standard rejection rule accepts the longest
  matching prefix plus one correction/bonus token, so every tick
  retires between 1 and k+1 tokens per slot and **greedy output is
  bit-exact** to the non-speculative engine (``tests/
  test_speculative.py`` asserts it on both cache layouts).  Rollback
  past the first rejection is free by the mask invariant: rejected
  K/V rows sit at positions ``>= length`` — unreachable (the
  attention mask is ``position < length``) and overwritten by later
  writes.  On the paged cache the admission reservation is k-aware
  (``pages_needed(..., extra=k)``) so verify writes always land in
  pages the slot already owns: no mid-speculation allocation, no page
  leaks.

- **Chunked prefill** (Sarathi-Serve).  Long prompts are admitted as
  usual (slot + full worst-case page reservation) but prefilled in
  ``TP_SERVE_PREFILL_CHUNK``-token chunks, ONE chunk per engine tick,
  interleaved with decode — running slots no longer stall for a whole
  long-prompt prefill, which is what bounds decode tail latency and
  TTFT p99 under long-prompt traffic.  The rectangular engine feeds
  chunks through the same ``verify`` continuation program; the paged
  engine reuses its suffix-prefill buckets (chunk sizes round up to a
  page multiple so the whole-page scatter stays aligned), registering
  prefix pages chunk-at-a-time.  A slot mid-prefill is excluded from
  the decode batch (its verify/decode writes are routed to scratch)
  until its final chunk emits the first token.

Knobs: ``TP_SERVE_SPEC_K`` (0 = off), ``TP_SERVE_SPEC_DRAFT``
(checkpoint prefix for the draft), ``TP_SERVE_PREFILL_CHUNK`` (0 =
off), ``TP_SERVE_SPEC_DYNAMIC`` (1 = halve k when the batch is full —
speculation trades FLOPs for latency, and a full batch is already
compute-bound).  Telemetry: ``serve_spec_proposed_total`` /
``serve_spec_accepted_total`` / ``serve_spec_accept_rate`` /
``serve_prefill_chunks_total``.  See docs/speculative_decoding.md for
the verify math, the rejection rule, and the rollback/page contract.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from .. import telemetry, tracing
from ..analysis.race_checker import race_audit
from ..base import MXNetError, get_env
from .engine import bucket_batch, bucket_length
from .generate import GenerationEngine, KVTransformerLM, _GenPending, \
    _Seq
from .paged import PagedGenerationEngine

__all__ = ["DraftModel", "SpeculativeGenerationEngine",
           "PagedSpeculativeGenerationEngine"]


class DraftModel:
    """A small :class:`KVTransformerLM` proposing greedy candidate
    tokens, its rectangular KV cache kept in lockstep with the target
    engine's slots.

    The draft always uses the rectangular layout even under the paged
    target engine — it is small by construction (that is the point),
    so its worst-case rectangle is cheap, lockstep is a single
    ``lengths`` array, and there is no second block pool that could
    exhaust mid-flight.  ``model=None`` builds a shell for test
    doubles that override :meth:`propose`.
    """

    def __init__(self, model: Optional[KVTransformerLM]):
        self.model = model
        self.cache_k = None
        self.cache_v = None
        # per-slot cached-token counts, maintained by the engine in
        # lockstep with its own `_lengths` (loop-thread-owned)
        self.lengths: Optional[np.ndarray] = None
        self.max_slots = 0

    @classmethod
    def from_env(cls, target: KVTransformerLM) -> "DraftModel":
        """Load the draft checkpoint named by ``TP_SERVE_SPEC_DRAFT``
        (a ``save_checkpoint`` prefix, epoch 0).  Heads default to the
        target's (``TP_SERVE_SPEC_DRAFT_HEADS`` overrides); weight
        dtype follows ``TP_SERVE_SPEC_DRAFT_DTYPE`` (empty inherits
        ``TP_SERVE_WEIGHT_DTYPE``), so an int8 draft costs one env
        var."""
        prefix = get_env("SERVE_SPEC_DRAFT")
        if not prefix:
            raise MXNetError(
                "speculative decoding needs a draft model: pass "
                "draft= or set TP_SERVE_SPEC_DRAFT to a checkpoint "
                "prefix")
        from ..model import load_checkpoint

        _sym, arg_params, _aux = load_checkpoint(prefix, 0)
        heads = get_env("SERVE_SPEC_DRAFT_HEADS", 0, int) \
            or target.spec.heads
        dt = get_env("SERVE_SPEC_DRAFT_DTYPE") or None
        return cls(KVTransformerLM(arg_params, heads, weight_dtype=dt))

    def setup(self, max_slots: int, max_len: int) -> None:
        """Allocate the lockstep cache: same slot count and position
        budget as the target engine (+ the scratch slot)."""
        self.max_slots = int(max_slots)
        self.lengths = np.zeros(max_slots, np.int32)
        if self.model is not None:
            if max_len > self.model.spec.max_seq:
                raise MXNetError(
                    "draft position table (%d) is smaller than the "
                    "engine max_len (%d)"
                    % (self.model.spec.max_seq, max_len))
            self.cache_k, self.cache_v = self.model.init_cache(
                max_slots, max_len)

    def prefill(self, tokens: np.ndarray, lens: np.ndarray,
                slots: np.ndarray) -> None:
        """Ingest prompt K/V for the given slots (bucketed like the
        target's rectangular prefill; logits discarded)."""
        if self.model is None:
            return
        self.cache_k, self.cache_v, _ = self.model.prefill(
            self.cache_k, self.cache_v, tokens, lens, slots)

    def propose(self, tokens: np.ndarray, k: int) -> np.ndarray:
        """Greedily propose ``k`` tokens per slot: ``k + 1`` runs of
        the draft's one-compiled-decode.  ``tokens`` (slots,) is each
        slot's newest emitted token (no K/V yet, same convention as
        the target loop).  The extra final step pre-ingests the last
        proposal's K/V, which keeps the draft cache exactly one fed
        token behind in EVERY outcome — including a full accept, where
        the target's bonus token becomes the next tick's fed token and
        the draft must already hold K/V for all k proposals."""
        n = int(np.asarray(tokens).shape[0])
        drafts = np.zeros((n, int(k)), np.int32)
        if self.model is None:
            return drafts
        cur = np.array(tokens, np.int32)
        lens = np.array(self.lengths, np.int32)
        for j in range(int(k) + 1):
            self.cache_k, self.cache_v, logits = self.model.decode(
                self.cache_k, self.cache_v, cur, lens)
            lens += 1
            if j < k:
                cur = np.argmax(np.asarray(logits),
                                axis=-1).astype(np.int32)
                drafts[:, j] = cur
        return drafts


class _ChunkState:
    """Bookkeeping for one slot mid-chunked-prefill: progress lives in
    ``seq.length`` (tokens of the prompt already cached)."""

    __slots__ = ("req", "seq")

    def __init__(self, req: _GenPending, seq: _Seq):
        self.req = req
        self.seq = seq


class _SpecMixin:
    """The speculative + chunked-prefill loop, cache-layout agnostic.

    Subclasses bind it over :class:`GenerationEngine` (rectangular) or
    :class:`PagedGenerationEngine` via four hooks: ``_verify_batch``
    (one target pass over k+1 candidates), ``_chunk_prefill`` (one
    prompt chunk for a batch of mid-prefill slots), ``_chunk_size``
    (layout-legal chunk length) and ``_register_chunk`` (paged prefix
    registration).  MUST be configured (``_spec_configure``) before
    the base ``__init__`` runs — the base constructor starts the loop
    thread."""

    def _spec_configure(self, model: KVTransformerLM, *,
                        draft=None, spec_k: Optional[int] = None,
                        prefill_chunk: Optional[int] = None,
                        dynamic_k: Optional[bool] = None,
                        spec_seed: int = 0) -> None:
        self.spec_k = int(spec_k if spec_k is not None
                          else get_env("SERVE_SPEC_K", 0, int))
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else get_env("SERVE_PREFILL_CHUNK", 0, int))
        self.dynamic_k = bool(
            dynamic_k if dynamic_k is not None
            else get_env("SERVE_SPEC_DYNAMIC", 0, int))
        if self.spec_k < 0 or self.prefill_chunk < 0:
            raise MXNetError("spec_k and prefill_chunk must be >= 0")
        if draft is not None and not isinstance(draft, DraftModel):
            draft = DraftModel(draft)
        if draft is None and self.spec_k > 0:
            draft = DraftModel.from_env(model)
        if draft is not None and draft.model is not None \
                and draft.model.spec.vocab_size != model.spec.vocab_size:
            raise MXNetError(
                "draft vocab (%d) != target vocab (%d)"
                % (draft.model.spec.vocab_size, model.spec.vocab_size))
        self.draft = draft
        # engine-local mirrors (mutated under self._cond, mirrored
        # into model.stats under its own lock)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_runs = 0
        self.prefill_chunks = 0
        self._chunking: Dict[int, _ChunkState] = {}
        self._spec_rng = np.random.default_rng(spec_seed)

    # ------------------------------------------------------------ plumbing
    def _setup_cache(self) -> None:
        super()._setup_cache()
        if self.draft is not None:
            self.draft.setup(self.max_slots, self.max_len)

    def _spec_reserve_extra(self) -> int:
        # verify scatters k candidate K/V rows past the accepted
        # length — reserve them up front so rollback is free
        return self.spec_k if (self.draft is not None
                               and self.spec_k > 0) else 0

    def _release(self, slot: int) -> None:
        self._chunking.pop(slot, None)
        if self.draft is not None:
            self.draft.lengths[slot] = 0
        super()._release(slot)

    def _effective_k(self, n_active: int) -> int:
        """Dynamic-k: a full batch is already compute-bound, so spend
        fewer speculative FLOPs on it (halve k, floor 1)."""
        k = self.spec_k
        if self.dynamic_k and k > 1 and n_active >= self.max_slots:
            k = max(1, k // 2)
        return k

    # ----------------------------------------------------------- admission
    def _chunk_size(self) -> int:
        """Prompt tokens prefilled per tick (0 = chunking off; the
        paged engine rounds up to a page multiple)."""
        return self.prefill_chunk

    def _admit(self, reqs: List[_GenPending]) -> None:
        chunk = self._chunk_size()
        direct: List[_GenPending] = []
        chunked: List[_GenPending] = []
        for r in reqs:
            if chunk and r.tokens.size - r.shared_tokens > chunk:
                chunked.append(r)
            else:
                direct.append(r)
        if direct:
            super()._admit(direct)
            self._draft_ingest(direct)
        for r in chunked:
            self._seat_chunked(r)

    def _seat_chunked(self, r: _GenPending) -> None:
        """Seat a long-prompt request WITHOUT prefilling it: the slot
        holds only its shared prefix so far; ``_advance_chunks`` feeds
        one chunk per tick until the final chunk emits the first
        token.  ``last_token`` stays None meanwhile, which excludes
        the slot from the decode batch."""
        now = time.monotonic()
        if r.deadline is not None and now > r.deadline:
            self._abort_admission(r)
            with self.stats.lock:
                self.stats.expired += 1
            telemetry.counter("serve_deadline_expired_total").inc()
            if r.trace is not None:
                tracing.flag(r.trace, "deadline")
                tracing.record(r.trace, "serve.queue", r.t_submit, now)
            r.future.set_exception(MXNetError(
                "request deadline expired after %.1f ms in queue"
                % ((now - r.t_submit) * 1e3)))
            return
        slot = r.slot
        if slot is None:  # rectangular path: no up-front reservation
            slot = next(i for i, s in enumerate(self._seqs)
                        if s is None)
            r.slot = slot
        seq = _Seq(r, slot, r.tokens.size)
        seq.length = r.shared_tokens
        self._seqs[slot] = seq
        self._lengths[slot] = r.shared_tokens
        self._chunking[slot] = _ChunkState(r, seq)
        if r.trace is not None:
            # queue phase ends at seating; every later prefill chunk
            # extends the cursor from here
            tracing.record(r.trace, "serve.queue", r.t_submit, now)
            seq.t_cursor = now
        # the draft ingests the WHOLE prompt up front: chunking exists
        # to bound the TARGET's per-tick prefill compute, and the
        # draft is small by construction
        self._draft_ingest([r])

    def _draft_ingest(self, reqs: List[_GenPending]) -> None:
        """Prefill the draft cache with the full prompts of freshly
        seated requests (bucketed like the rectangular prefill).
        Requests that already finished inside ``_admit`` (1-token
        answers) have released their slot — nothing to ingest."""
        if self.draft is None or self.draft.model is None or not reqs:
            return
        byreq = {id(s.req): s for s in self._seqs if s is not None}
        seated = [(r, byreq[id(r)]) for r in reqs if id(r) in byreq]
        groups: Dict[int, List] = {}
        for r, seq in seated:
            L = bucket_length(r.tokens.size, self.max_len)
            groups.setdefault(L, []).append((r, seq))
        for L, group in sorted(groups.items()):
            for start in range(0, len(group), self.max_slots):
                part = group[start:start + self.max_slots]
                nb = bucket_batch(len(part), self.max_slots)
                toks = np.zeros((nb, L), np.int32)
                lens = np.ones(nb, np.int32)
                slots = np.full(nb, self.max_slots, np.int32)
                for j, (r, seq) in enumerate(part):
                    toks[j, :r.tokens.size] = r.tokens
                    lens[j] = r.tokens.size
                    slots[j] = seq.slot
                self.draft.prefill(toks, lens, slots)
                for r, seq in part:
                    self.draft.lengths[seq.slot] = r.tokens.size

    # ---------------------------------------------------------- chunk ticks
    def _advance_chunks(self) -> None:
        """Feed ONE prompt chunk to every mid-prefill slot (batched at
        a single length bucket) — the interleaving that keeps decode
        ticks flowing between chunks."""
        if not self._chunking:
            return
        now = time.monotonic()
        for slot in list(self._chunking):
            st = self._chunking[slot]
            if st.req.deadline is not None and now > st.req.deadline:
                self._release(slot)  # pops the chunk state too
                with self.stats.lock:
                    self.stats.expired += 1
                telemetry.counter("serve_deadline_expired_total").inc()
                if st.req.trace is not None:
                    tracing.flag(st.req.trace, "deadline")
                    tracing.record(st.req.trace, "serve.prefill",
                                   st.seq.t_cursor, now)
                st.req.future.set_exception(MXNetError(
                    "request deadline expired after %.1f ms mid-"
                    "prefill" % ((now - st.req.t_submit) * 1e3)))
        if not self._chunking:
            return
        chunk = self._chunk_size()
        items = sorted(self._chunking.items())
        n = len(items)
        takes = np.ones(n, np.int32)
        for j, (slot, st) in enumerate(items):
            takes[j] = min(chunk, st.req.tokens.size - st.seq.length)
        L = bucket_length(int(takes.max()), self.max_len)
        nb = bucket_batch(n, self.max_slots)
        toks = np.zeros((nb, L), np.int32)
        starts = np.zeros(nb, np.int32)
        tk = np.ones(nb, np.int32)
        slots = np.full(nb, -1, np.int32)
        for j, (slot, st) in enumerate(items):
            lo = st.seq.length
            toks[j, :takes[j]] = st.req.tokens[lo:lo + takes[j]]
            starts[j] = lo
            tk[j] = takes[j]
            slots[j] = slot
        npref = int(takes.sum())
        with self._cond:
            self.prefill_tokens += npref
            self.prefill_chunks += n
        with self.stats.lock:
            self.stats.prefill_chunks += n
        telemetry.counter("serve_prefill_tokens_total").inc(npref)
        telemetry.counter("serve_prefill_chunks_total").inc(n)
        logits = self._chunk_prefill(toks, starts, tk, slots)
        now = time.monotonic()
        for j, (slot, st) in enumerate(items):
            st.seq.length += int(takes[j])
            self._lengths[slot] = st.seq.length
            self._register_chunk(st)
            if st.req.trace is not None:
                # one prefill span per chunk, cursor-contiguous: the
                # wait since the previous tick is part of the chunk
                tracing.record(st.req.trace, "serve.prefill",
                               st.seq.t_cursor, now,
                               {"chunk_tokens": int(takes[j])})
                st.seq.t_cursor = now
            if st.seq.length >= st.req.tokens.size:
                # final chunk: TTFT ends here — sample the first token
                # through the same path as a direct admission
                del self._chunking[slot]
                self._emit(st.seq, logits[j], now)

    def _chunk_prefill(self, toks: np.ndarray, starts: np.ndarray,
                       takes: np.ndarray, slots: np.ndarray
                       ) -> np.ndarray:
        """Run one chunk bucket; returns per-row logits at each row's
        final chunk position.  Rectangular: the ``verify`` program IS
        the continuation prefill (all-position logits; take the
        last real one)."""
        rows = np.where(slots >= 0, slots,
                        self.max_slots).astype(np.int32)
        lens = np.zeros(rows.shape[0], np.int32)
        lens[slots >= 0] = starts[slots >= 0]
        self._cache_k, self._cache_v, logits = self.model.verify(
            self._cache_k, self._cache_v, toks, lens, rows)
        logits = np.asarray(logits)
        return logits[np.arange(rows.shape[0]), takes - 1]

    def _register_chunk(self, st: _ChunkState) -> None:
        """Hook: the paged engine content-addresses completed prompt
        pages chunk-at-a-time."""

    # ---------------------------------------------------------- decode tick
    def _decode_step(self) -> None:
        self._advance_chunks()
        active = [s for s in self._seqs
                  if s is not None and s.last_token is not None]
        if not active:
            return
        use_spec = self.draft is not None and self.spec_k > 0
        k = self._effective_k(len(active)) if use_spec else 0
        if k <= 0:
            self._plain_tick(active)
        else:
            self._spec_tick(active, k)

    def _plain_tick(self, active: List[_Seq]) -> None:
        """The base single-token decode over the ACTIVE slots only
        (mid-prefill slots are excluded; their table rows still feed
        the program but their writes land at positions their next
        chunk overwrites)."""
        tokens = np.zeros(self.max_slots, np.int32)
        for seq in active:
            tokens[seq.slot] = seq.last_token
        with self._cond:
            self.active_high_water = max(self.active_high_water,
                                         len(active))
        telemetry.histogram("serve_decode_active").observe(len(active))
        logits = np.asarray(self._decode_batch(tokens))
        now = time.monotonic()
        for seq in active:
            seq.length += 1
            self._lengths[seq.slot] = seq.length
            if seq.req.trace is not None:
                # before _emit — a finishing sequence settles (and
                # finalizes its trace) inside _emit
                tracing.record(seq.req.trace, "serve.decode_tick",
                               seq.t_cursor, now)
                seq.t_cursor = now
            self._emit(seq, logits[seq.slot], now)
            if (self._seqs[seq.slot] is seq
                    and seq.req.deadline is not None
                    and now > seq.req.deadline):
                self._finish(seq)

    def _spec_tick(self, active: List[_Seq], k: int) -> None:
        """One speculative iteration: k draft proposals per slot, ONE
        target verify pass over the k+1 candidates, longest-matching-
        prefix acceptance, both caches rolled forward to the accepted
        length (rollback = not advancing past it)."""
        tokens = np.zeros(self.max_slots, np.int32)
        amask = np.zeros(self.max_slots, bool)
        for seq in active:
            tokens[seq.slot] = seq.last_token
            amask[seq.slot] = True
        with self._cond:
            self.active_high_water = max(self.active_high_water,
                                         len(active))
        telemetry.histogram("serve_decode_active").observe(len(active))
        t_d0 = time.monotonic()
        drafts = self.draft.propose(tokens, k)     # (slots, k)
        cand = np.concatenate([tokens[:, None], drafts], axis=1)
        t_v0 = time.monotonic()
        logits = self._verify_batch(cand, amask)   # (slots, k+1, V)
        now = time.monotonic()
        proposed = accepted = 0
        for seq in active:
            i = seq.slot
            toks, rows, matched = self._accept(seq, drafts[i],
                                               logits[i])
            proposed += k
            accepted += matched
            kept = self._emit_run(seq, toks, rows, now, finish=False)
            if seq.req.trace is not None:
                tick = tracing.record(
                    seq.req.trace, "serve.decode_tick",
                    seq.t_cursor, now,
                    {"kind": "spec", "proposed": int(k),
                     "accepted": int(matched)})
                seq.t_cursor = now
                if tick is not None:
                    # batch-wide draft/verify sub-phases, parented
                    # under this tick — overlap detail, not summed
                    tracing.record(seq.req.trace, "serve.draft",
                                   t_d0, t_v0, None, tick)
                    tracing.record(seq.req.trace, "serve.verify",
                                   t_v0, now, None, tick)
            # every kept token except the newest has K/V from the
            # verify scatter; candidates past `kept` are now stale —
            # unreachable through the mask, overwritten later
            seq.length += kept
            self._lengths[i] = seq.length
            self.draft.lengths[i] = seq.length
            if seq.done or (seq.req.deadline is not None
                            and now > seq.req.deadline):
                self._finish(seq)
        with self._cond:
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            self.spec_runs += 1
        with self.stats.lock:
            self.stats.spec_proposed += proposed
            self.stats.spec_accepted += accepted
            self.stats.spec_runs += 1
        telemetry.counter("serve_spec_proposed_total").inc(proposed)
        telemetry.counter("serve_spec_accepted_total").inc(accepted)
        telemetry.gauge("serve_spec_accept_rate").set(
            self.spec_accepted / max(1, self.spec_proposed))

    def _verify_batch(self, cand: np.ndarray,
                      active: np.ndarray) -> np.ndarray:
        """ONE target pass over (slots, k+1) candidates.  Rectangular:
        inactive rows (free or mid-prefill slots) scatter to the
        scratch slot so candidate garbage cannot touch real cache
        rows a later chunk expects to own."""
        rows = np.where(active, np.arange(self.max_slots),
                        self.max_slots).astype(np.int32)
        self._cache_k, self._cache_v, logits = self.model.verify(
            self._cache_k, self._cache_v, cand, self._lengths, rows)
        return np.asarray(logits)

    # ----------------------------------------------------------- acceptance
    def _accept(self, seq: _Seq, drafts: np.ndarray,
                vlogits: np.ndarray):
        """Apply the rejection rule to one slot's verify logits
        (k+1, V).  Greedy: accept while the draft equals the target
        argmax; the first mismatching position contributes the
        target's own token (correction), a full match contributes the
        bonus token — identical, token for token, to running the
        sequential greedy decode.  Temperature: standard speculative
        sampling with the greedy draft as a point-mass proposal:
        accept d with prob p(d); on rejection resample from p with
        d's mass removed; on full acceptance take a bonus sample.
        Returns (tokens, logits_rows, matched_draft_count)."""
        k = int(drafts.shape[0])
        temp = seq.req.temperature
        if temp <= 0.0:
            t = np.argmax(vlogits, axis=-1)
            a = 0
            while a < k and int(t[a]) == int(drafts[a]):
                a += 1
            idx = list(range(a + 1))
            return ([int(t[j]) for j in idx],
                    [vlogits[j] for j in idx], a)
        toks: List[int] = []
        rows: List[np.ndarray] = []
        matched = 0
        for j in range(k):
            p = self._target_probs(vlogits[j], temp, seq.req.top_k)
            d = int(drafts[j])
            rows.append(vlogits[j])
            if self._spec_rng.random() < p[d]:
                toks.append(d)
                matched += 1
                continue
            q = p.copy()
            q[d] = 0.0
            s = q.sum()
            if s <= 0.0:  # p was a point mass on d: keep it
                toks.append(d)
                matched += 1
                continue
            toks.append(int(self._spec_rng.choice(p.size, p=q / s)))
            return toks, rows, matched
        p = self._target_probs(vlogits[k], temp, seq.req.top_k)
        toks.append(int(self._spec_rng.choice(p.size, p=p)))
        rows.append(vlogits[k])
        return toks, rows, matched

    @staticmethod
    def _target_probs(logits: np.ndarray, temperature: float,
                      top_k: int) -> np.ndarray:
        """Host replica of ``KVTransformerLM.sample``'s policy
        (temperature scaling, optional top-k truncation, softmax).
        The stochastic path draws from the engine's own RNG stream, so
        it matches the non-speculative DISTRIBUTION, not its exact
        sample sequence (greedy is the bit-exact mode)."""
        x = np.asarray(logits, np.float64) / float(temperature)
        if top_k:
            kth = np.partition(x, -int(top_k))[-int(top_k)]
            x = np.where(x < kth, -np.inf, x)
        x = x - x.max()
        p = np.exp(x)
        return p / p.sum()


@race_audit(exempt=("_seqs", "_lengths", "_cache_k", "_cache_v",
                    "_key", "prefill_tokens", "active_high_water",
                    "spec_proposed", "spec_accepted", "spec_runs",
                    "prefill_chunks", "_chunking"))
class SpeculativeGenerationEngine(_SpecMixin, GenerationEngine):
    """:class:`GenerationEngine` (rectangular cache) with speculative
    decoding and chunked prefill.  ``spec_k=0`` with a positive
    ``prefill_chunk`` gives chunked prefill alone; greedy output is
    bit-exact to the plain engine in every configuration."""

    def __init__(self, model: KVTransformerLM, *, draft=None,
                 spec_k: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 dynamic_k: Optional[bool] = None, **kw):
        self._spec_configure(model, draft=draft, spec_k=spec_k,
                             prefill_chunk=prefill_chunk,
                             dynamic_k=dynamic_k,
                             spec_seed=kw.get("seed", 0))
        kw.setdefault("name", "serve_spec_lm")
        super().__init__(model, **kw)


@race_audit(exempt=("_seqs", "_lengths", "_cache_k", "_cache_v",
                    "_key", "prefill_tokens", "active_high_water",
                    "spec_proposed", "spec_accepted", "spec_runs",
                    "prefill_chunks", "_chunking"))
class PagedSpeculativeGenerationEngine(_SpecMixin,
                                       PagedGenerationEngine):
    """:class:`PagedGenerationEngine` with speculative decoding and
    chunked prefill.  Admission reserves ``pages_needed(prompt,
    max_new, extra=k)`` so the verify scatter always lands in owned
    pages (rollback cannot leak); chunk sizes round up to a page
    multiple so chunk boundaries stay page-aligned for the whole-page
    prefill scatter and chunk-at-a-time prefix registration."""

    def __init__(self, model: KVTransformerLM, *, draft=None,
                 spec_k: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 dynamic_k: Optional[bool] = None, **kw):
        self._spec_configure(model, draft=draft, spec_k=spec_k,
                             prefill_chunk=prefill_chunk,
                             dynamic_k=dynamic_k,
                             spec_seed=kw.get("seed", 0))
        kw.setdefault("name", "serve_spec_paged_lm")
        super().__init__(model, **kw)

    def _chunk_size(self) -> int:
        c = self.prefill_chunk
        if c <= 0:
            return 0
        P = self._kv.page_tokens
        return -(-c // P) * P

    def _chunk_prefill(self, toks: np.ndarray, starts: np.ndarray,
                       takes: np.ndarray, slots: np.ndarray
                       ) -> np.ndarray:
        # the existing suffix-prefill program: `starts` (page-aligned
        # by _chunk_size) is the prefix already cached, the chunk is
        # the suffix — last-position logits come back directly
        return np.asarray(
            self._kv.prefill(toks, starts, takes, slots))

    def _register_chunk(self, st: _ChunkState) -> None:
        # content-address the pages this chunk completed (idempotent
        # for pages registered by earlier chunks)
        self._kv.register_prompt(st.seq.slot, st.req.tokens,
                                 upto=st.seq.length)

    def _verify_batch(self, cand: np.ndarray,
                      active: np.ndarray) -> np.ndarray:
        # inactive rows gather/scatter through scratch pages — a slot
        # mid-chunked-prefill owns real pages its next chunk will
        # fill, and candidate garbage must not touch them
        return np.asarray(
            self._kv.verify(cand, self._lengths, active=active))
