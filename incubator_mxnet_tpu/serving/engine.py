"""Thread-safe inference engine: request queue + bucketed dynamic batching.

Not in the reference (v0.11 stops at the single-request C predict API,
``src/c_api/c_predict_api.cc``); this is the Orca/Clipper-style serving
layer the ROADMAP's "heavy traffic" north star needs.  Design contract
with XLA: every launched program has a shape seen before or a shape from
a SMALL closed set — requests are coalesced into **padded power-of-two
batch buckets**, so a mixed-shape request stream compiles at most one
program per (bucket, phase) instead of one per arrival pattern.

- :class:`InferenceEngine` — generic batcher over any ``batch_fn`` that
  maps a stacked input dict to a list of stacked outputs (axis 0 =
  batch).  ``submit`` returns a ``concurrent.futures.Future``; a
  background batcher thread groups compatible requests (same per-request
  shape/dtype signature), pads the group to the next power of two, runs
  the batch, and slices results back per request.
- Admission control: a bounded queue (``TP_SERVE_MAX_QUEUE``) rejects
  new work with ``MXNetError`` instead of building unbounded latency —
  backpressure belongs at the edge, not in the queue.
- Per-request deadlines: a request that waited past its deadline fails
  fast with ``MXNetError`` and never occupies a device slot.  All
  deadline and flush timing uses ``time.monotonic()`` — wall clock can
  step (NTP, suspend) and must never enter deadline math.

Telemetry (``TP_TELEMETRY=1``): ``serve_queue_depth``,
``serve_batch_size``, ``serve_padding_waste``,
``serve_request_seconds``, ``serve_requests_total``,
``serve_rejected_total``, ``serve_deadline_expired_total``,
``serve_compiles_total{phase=...}``, ``serve_batcher_deaths_total``.
See docs/serving.md.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..analysis.race_checker import race_audit
from ..base import MXNetError, get_env

__all__ = ["InferenceEngine", "bucket_batch", "bucket_length"]


def bucket_batch(n: int, max_batch: int) -> int:
    """Next power of two ≥ n, capped at ``max_batch`` (the batch-bucket
    ladder: 1, 2, 4, ... — log2(max_batch)+1 compiled programs cover
    every group size)."""
    b = 1
    while b < n and b < max_batch:
        b <<= 1
    return min(b, max_batch)


def bucket_length(n: int, cap: Optional[int] = None) -> int:
    """Next power of two ≥ n, optionally capped (the sequence-length
    ladder for prompt prefill)."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap) if cap is not None else b


class _Pending:
    __slots__ = ("inputs", "future", "sig", "deadline", "t_submit")

    def __init__(self, inputs, future, sig, deadline):
        self.inputs = inputs
        self.future = future
        self.sig = sig
        self.deadline = deadline
        self.t_submit = time.monotonic()


class ServeStats:
    """Host-side mirror of the serve telemetry (always on, so benches
    and tests read it without enabling the global registry)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.requests = 0
        self.rejected = 0
        self.expired = 0
        self.batches = 0
        self.rows = 0
        self.padded_rows = 0
        self.compile_keys = set()
        # speculative-decoding / chunked-prefill mirrors (filled by
        # serving.speculative; stay 0 on plain engines)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_runs = 0
        self.prefill_chunks = 0

    @property
    def spec_accept_rate(self) -> float:
        """Fraction of proposed draft tokens the target accepted."""
        return (self.spec_accepted / self.spec_proposed
                if self.spec_proposed else 0.0)

    @property
    def num_compiles(self) -> int:
        return len(self.compile_keys)

    @property
    def padding_waste(self) -> float:
        """Fraction of launched batch rows that were padding."""
        return self.padded_rows / self.rows if self.rows else 0.0

    def snapshot(self) -> Dict[str, float]:
        """One consistent read of every counter, taken under the lock.

        The fleet router's ``load_report()`` heartbeat reads these from
        a different thread than the serve loop that mutates them — a
        field-by-field unlocked read could observe e.g. ``spec_accepted``
        from one verify run and ``spec_proposed`` from the next, so the
        probe contract is: mirrors leave this object only via snapshot.
        """
        with self.lock:
            return {
                "requests": self.requests,
                "rejected": self.rejected,
                "expired": self.expired,
                "batches": self.batches,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "num_compiles": len(self.compile_keys),
                "spec_proposed": self.spec_proposed,
                "spec_accepted": self.spec_accepted,
                "spec_runs": self.spec_runs,
                "spec_accept_rate": (self.spec_accepted
                                     / self.spec_proposed
                                     if self.spec_proposed else 0.0),
                "prefill_chunks": self.prefill_chunks,
            }

    def record_batch(self, key, n: int, bucket: int, phase: str) -> None:
        with self.lock:
            self.batches += 1
            self.rows += bucket
            self.padded_rows += bucket - n
            fresh = key not in self.compile_keys
            if fresh:
                self.compile_keys.add(key)
        if fresh:
            telemetry.counter("serve_compiles_total",
                              {"phase": phase}).inc()
        telemetry.histogram("serve_batch_size").observe(n)
        telemetry.histogram("serve_padding_waste").observe(
            (bucket - n) / bucket)


@race_audit
class InferenceEngine:
    """Dynamic batcher over a stacked-batch forward function.

    ``batch_fn(inputs)`` receives ``{name: np.ndarray}`` with a leading
    batch axis (always a power-of-two bucket size) and returns a
    sequence of stacked outputs.  Per-request inputs submitted to
    :meth:`submit` carry NO batch axis; the engine stacks, pads (by
    repeating the first row — real values, so no NaN poison), runs, and
    slices row ``i`` of every output back to request ``i``.

    Parameters
    ----------
    batch_fn : the compiled forward (e.g. a ``jax.jit`` that retraces
        per shape — each bucket shape compiles once, which is the point)
    max_batch : largest bucket (env ``TP_SERVE_MAX_BATCH``, default 32)
    max_delay_ms : how long the batcher holds an incomplete bucket open
        for more arrivals (env ``TP_SERVE_MAX_DELAY_MS``, default 2.0)
    max_queue : admission bound; ``submit`` beyond it raises
        ``MXNetError`` (env ``TP_SERVE_MAX_QUEUE``, default 256)
    """

    def __init__(self, batch_fn: Callable[[Dict[str, np.ndarray]],
                                          Sequence[np.ndarray]],
                 *, max_batch: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 name: str = "serve"):
        self._batch_fn = batch_fn
        self.max_batch = int(max_batch if max_batch is not None
                             else get_env("SERVE_MAX_BATCH", 32, int))
        self.max_delay = float(
            max_delay_ms if max_delay_ms is not None
            else get_env("SERVE_MAX_DELAY_MS", 2.0, float)) / 1e3
        self.max_queue = int(max_queue if max_queue is not None
                             else get_env("SERVE_MAX_QUEUE", 256, int))
        if self.max_batch < 1:
            raise MXNetError("max_batch must be >= 1")
        self.name = name
        self.stats = ServeStats()
        self._queue: List[_Pending] = []
        self._cond = threading.Condition()
        self._closed = False
        self._worker_exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._batcher_loop, name=name + "-batcher", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ client API
    def submit(self, inputs: Dict[str, np.ndarray], *,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the list
        of per-request output arrays.  Raises ``MXNetError`` when the
        queue is full (admission control) or the engine is closed."""
        arrs = {n: np.asarray(v) for n, v in inputs.items()}
        sig = tuple(sorted((n, a.shape, str(a.dtype))
                           for n, a in arrs.items()))
        fut: Future = Future()
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        with self._cond:
            if self._worker_exc is not None:
                # fail-fast: a dead batcher thread must not let callers
                # enqueue futures that can never resolve
                raise MXNetError(
                    "engine %r batcher thread died: %r — engine is "
                    "unusable, create a new one"
                    % (self.name, self._worker_exc)) from self._worker_exc
            if self._closed:
                raise MXNetError("engine %r is closed" % self.name)
            if len(self._queue) >= self.max_queue:
                self.stats.rejected += 1
                telemetry.counter("serve_rejected_total").inc()
                raise MXNetError(
                    "serve queue full (%d >= max_queue=%d): backpressure"
                    % (len(self._queue), self.max_queue))
            self._queue.append(_Pending(arrs, fut, sig, deadline))
            telemetry.gauge("serve_queue_depth").set(len(self._queue))
            self._cond.notify_all()
        return fut

    def predict(self, timeout: Optional[float] = 60.0,
                **inputs) -> List[np.ndarray]:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(inputs).result(timeout=timeout)

    def close(self) -> None:
        """Stop the batcher; pending requests fail with ``MXNetError``."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            pending, self._queue = self._queue, []
            self._cond.notify_all()
        for p in pending:
            p.future.set_exception(
                MXNetError("engine %r closed" % self.name))
        self._thread.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------------- batcher thread
    def _expire(self, now: float) -> None:
        """Fail queued requests that outlived their deadline (must hold
        the lock)."""
        alive = []
        for p in self._queue:
            if p.deadline is not None and now > p.deadline:
                self.stats.expired += 1
                telemetry.counter("serve_deadline_expired_total").inc()
                p.future.set_exception(MXNetError(
                    "request deadline expired after %.1f ms in queue"
                    % ((now - p.t_submit) * 1e3)))
            else:
                alive.append(p)
        self._queue[:] = alive

    def _take_group(self) -> Optional[List[_Pending]]:
        """Pull the next same-signature group, holding an incomplete
        bucket open up to ``max_delay`` past its oldest arrival.  Runs
        inside the lock; returns None when closed and drained."""
        while True:
            if self._queue:
                self._expire(time.monotonic())
            if not self._queue:
                if self._closed:
                    return None
                self._cond.wait(timeout=0.1)
                continue
            head = self._queue[0]
            group = [p for p in self._queue if p.sig == head.sig]
            group = group[:self.max_batch]
            flush_at = head.t_submit + self.max_delay
            now = time.monotonic()
            if len(group) >= self.max_batch or now >= flush_at \
                    or self._closed:
                for p in group:
                    self._queue.remove(p)
                telemetry.gauge("serve_queue_depth").set(len(self._queue))
                return group
            self._cond.wait(timeout=flush_at - now)

    def _batcher_loop(self) -> None:
        group: Optional[List[_Pending]] = None
        try:
            while True:
                with self._cond:
                    group = self._take_group()
                if group is None:
                    return
                self._run_group(group)
                group = None
        except BaseException as exc:  # noqa — recorded, re-raised in submit()
            self._die(exc, group)

    def _die(self, exc: BaseException,
             group: Optional[List[_Pending]] = None) -> None:
        """The batcher thread died outside the per-future ``batch_fn``
        handler (e.g. stacking a malformed input).  Fail every pending and
        in-flight future now — a silent dead worker would leave clients
        blocked on futures that can never resolve — and remember the
        exception so the next :meth:`submit` re-raises it."""
        with self._cond:
            self._worker_exc = exc
            self._closed = True
            pending, self._queue = self._queue, []
            self._cond.notify_all()
        telemetry.counter("serve_batcher_deaths_total").inc()
        for p in (group or []) + pending:
            if not p.future.done():
                p.future.set_exception(MXNetError(
                    "engine %r batcher died: %r" % (self.name, exc)))

    def _run_group(self, group: List[_Pending]) -> None:
        n = len(group)
        bucket = bucket_batch(n, self.max_batch)
        names = list(group[0].inputs)
        batch = {}
        for name in names:
            rows = [p.inputs[name] for p in group]
            # pad to the bucket with copies of row 0: real values keep
            # the padded rows numerically inert (no NaN/inf surprises
            # feeding XLA), and they are sliced off before delivery
            rows += [rows[0]] * (bucket - n)
            batch[name] = np.stack(rows, axis=0)
        key = ("forward", group[0].sig, bucket)
        self.stats.record_batch(key, n, bucket, "forward")
        t0 = time.monotonic()
        try:
            outs = [np.asarray(o) for o in self._batch_fn(batch)]
        except Exception as e:  # noqa: BLE001 — delivered per-future
            for p in group:
                p.future.set_exception(e)
            return
        now = time.monotonic()
        telemetry.histogram("serve_batch_seconds").observe(now - t0)
        with self.stats.lock:
            self.stats.requests += len(group)
        for i, p in enumerate(group):
            telemetry.counter("serve_requests_total").inc()
            telemetry.histogram("serve_request_seconds").observe(
                now - p.t_submit)
            p.future.set_result([o[i] for o in outs])

    # ------------------------------------------------------------- factories
    @classmethod
    def from_symbol(cls, symbol, arg_params, aux_params,
                    input_shapes: Dict[str, Sequence[int]],
                    input_dtypes: Optional[Dict] = None,
                    weight_dtype: Optional[str] = None, **kw):
        """Serve a loaded symbol+params pair (the Predictor pair) with
        dynamic batching: ``input_shapes`` are PER-REQUEST shapes (no
        batch axis); the jitted forward retraces per batch bucket, so a
        mixed-load stream compiles once per bucket.

        ``weight_dtype='int8'`` (env ``TP_SERVE_WEIGHT_DTYPE``) parks
        every 2-D float ``*weight`` parameter as int8 + per-output-
        channel scale and dequantizes INSIDE the jitted forward — the
        HBM-resident copy is int8 (docs/quantization.md)."""
        import jax

        from ..lowering import lower_symbol

        if weight_dtype is None:
            weight_dtype = get_env("SERVE_WEIGHT_DTYPE") or None
        if weight_dtype in ("", "float32", "f32"):
            weight_dtype = None
        if weight_dtype not in (None, "int8"):
            raise MXNetError("weight_dtype must be None or 'int8', "
                             "got %r" % (weight_dtype,))

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        for n in input_shapes:
            if n not in arg_names:
                raise MXNetError("input %r is not an argument of the "
                                 "symbol" % (n,))
        probe = {n: (1,) + tuple(s) for n, s in input_shapes.items()}
        arg_shapes, _, aux_shapes = symbol.infer_shape(**probe)
        shape_of = dict(zip(arg_names, arg_shapes))
        dtypes = dict(input_dtypes or {})

        def park(src, name):
            v = (src or {}).get(name)
            if v is None:
                if "label" in name:
                    return None  # rebuilt per batch bucket
                raise MXNetError("missing parameter %r" % (name,))
            a = np.asarray(v.data if hasattr(v, "data") else v)
            if a.dtype == np.float64:
                a = a.astype(np.float32)
            return a

        host = {n: park(arg_params, n)
                for n in arg_names if n not in input_shapes}
        aux = {n: jax.device_put(park(aux_params, n))
               for n in aux_names}
        label_names = [n for n, v in host.items() if v is None]
        label_shape = {n: tuple(shape_of[n][1:]) for n in label_names}
        for n in label_names:
            del host[n]

        params, qparams = {}, {}
        weight_bytes = 0
        for n, a in host.items():
            if (weight_dtype == "int8" and a.ndim == 2
                    and n.endswith("weight")
                    and np.issubdtype(a.dtype, np.floating)):
                from ..quant.int8 import quantize_rowwise

                q, scale = quantize_rowwise(a)
                qparams[n] = (jax.device_put(q), jax.device_put(scale))
                weight_bytes += q.nbytes + scale.nbytes
            else:
                params[n] = jax.device_put(a)
                weight_bytes += a.nbytes
        if weight_dtype == "int8":
            telemetry.gauge("quant_weight_bytes",
                            {"component": "engine"}).set(weight_bytes)

        fwd = lower_symbol(symbol, is_train=False)
        key = jax.random.PRNGKey(0)

        @jax.jit
        def run(inputs):
            import jax.numpy as jnp

            args = dict(params)
            for n, (q, s) in qparams.items():
                # dequant inside the compiled program: int8 lives in
                # HBM, the f32 view exists only transiently
                args[n] = q.astype(jnp.float32) * s[:, None]
            args.update(inputs)
            b = next(iter(inputs.values())).shape[0]
            for n in label_names:
                # loss-head labels are dead at inference; bind zeros of
                # the bucket's batch shape (C predict API convention)
                args[n] = jnp.zeros((b,) + label_shape[n], jnp.float32)
            outs, _ = fwd(args, aux, key)
            return outs

        def batch_fn(batch):
            staged = {}
            for n, a in batch.items():
                want = np.dtype(dtypes.get(n, np.float32))
                staged[n] = np.ascontiguousarray(a, dtype=want)
            return run(staged)

        return cls(batch_fn, **kw)
