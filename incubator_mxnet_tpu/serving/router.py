"""Fleet front door: a multi-replica serving router with prefix-aware
placement, tenant quotas, deadline shedding, health-checked failover,
and live draining.

The single-replica stack (continuous batching, paged KV with prefix
caching, speculative decoding) scales *up*; this module scales *out*
(ROADMAP item: the "millions of users" story is horizontal).  A
:class:`ServingRouter` fronts N replicas — in-process engines wrapped
in :class:`EngineReplica`, or remote engines behind a
:class:`ReplicaServer` reached through :class:`TcpReplica` over the
``ps.py`` length-prefixed-pickle framing — and places each request by:

1. **Session affinity** — a sticky ``session -> replica`` map with a
   TTL (``TP_ROUTER_SESSION_TTL_S``): a conversation keeps landing
   where its KV prefix already lives.
2. **Prefix-aware placement** — the router mirrors each replica's
   registered prefix-hash chains (fed by the ``engine.load_report()``
   heartbeat probe) and scores candidates by the longest leading
   match of the request's own rolling blake2b chain
   (``paged.prefix_hashes``).  Equal digests mean equal whole
   prefixes, so the score is exactly the token count the replica's
   prefill would skip.  Between heartbeats the mirror is extended
   optimistically with the chains of requests just routed there.
3. **Power-of-two-choices fallback** — no prefix signal: sample two
   candidates, take the less loaded (load = (active + queued +
   placed-since-report) / slots).  ``TP_ROUTER_POLICY`` selects
   ``prefix`` (default), ``p2c``, or ``round_robin``.

Goodput protection happens **at admission, never after prefill
spend**: per-tenant token buckets (:class:`TenantQuota`, LM tokens per
second), deadline classes (``interactive`` / ``batch`` with default
SLOs ``TP_ROUTER_INTERACTIVE_SLO_MS`` / ``TP_ROUTER_BATCH_SLO_MS``),
and an ETA estimate per replica (queue depth x the engine's completed-
request EWMA) — a request no live replica can finish inside
``slack * deadline`` is rejected synchronously from :meth:`submit`
with ``MXNetError`` instead of being queued to miss its SLO after
burning prefill FLOPs.

Health: a heartbeat thread polls ``load_report()`` every
``TP_ROUTER_HEARTBEAT_S``; a replica silent past ``TP_ROUTER_DEAD_S``
(the ps.py ``_deadnode_timeout`` idiom) is marked dead — its in-flight
requests fail fast, and retryable ones re-route to a surviving replica
(at most ``TP_ROUTER_RETRIES`` times; the router future resolves
exactly once, first settle wins).  :meth:`drain` stops new placements
on one replica, waits for its in-flight work, then detaches it — the
zero-downtime deploy primitive.

Locking: ONE router condition guards every mutable field; replica
calls (``submit`` / ``load_report``) always happen OUTSIDE it, so the
router lock never nests around an engine lock and never holds across
network or device waits (the ``tools/lint.py`` locks pass covers this
module).

Telemetry: ``fleet_requests_total{tenant,class}``,
``fleet_routed_prefix_hits_total``, ``fleet_prefix_hit_tokens_total``,
``fleet_shed_total{reason,class}``, ``fleet_replica_dead_total``,
``fleet_retries_total``, ``fleet_drain_seconds``,
``fleet_request_seconds{class}``, ``fleet_slo_attainment{class}``,
``fleet_replicas_alive``.  See docs/fleet_serving.md.

Tracing (docs/tracing.md): when ``TP_TRACING`` is on, ``submit``
opens the root ``serve.request`` span at admission, records the
``router.admit``/``router.shed`` phases, ships the context to the
replica inside the submit ``kw`` (and the ps.py framing for TCP
replicas), and closes the trace at settle — flagging shed, errored,
and deadline-busting requests so tail sampling always keeps them.
"""
from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from .. import ps as _ps
from .. import telemetry, tracing
from ..analysis.race_checker import race_audit
from ..base import MXNetError, get_env
from .generate import GenerationResult
from .paged import prefix_hashes

__all__ = ["Replica", "EngineReplica", "ReplicaServer", "TcpReplica",
           "TenantQuota", "ServingRouter"]

DEADLINE_CLASSES = ("interactive", "batch")


# ---------------------------------------------------------------------------
# replica handles
# ---------------------------------------------------------------------------


class Replica:
    """What the router needs from a replica — a tiny protocol so an
    in-process engine and a TCP-backed remote engine interchange.

    ``name`` must be unique within one router.  ``submit`` mirrors
    ``GenerationEngine.submit`` (returns a Future of
    :class:`~.generate.GenerationResult`, raises ``MXNetError``
    synchronously on rejection); ``load_report`` mirrors
    ``GenerationEngine.load_report``.
    """

    name = "replica"

    def submit(self, tokens, max_new_tokens: int = 16, **kw) -> Future:
        raise NotImplementedError

    def load_report(self) -> Dict[str, object]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class EngineReplica(Replica):
    """In-process replica: a named handle over one engine (any
    :class:`~.generate.GenerationEngine` subclass).  The wrapper exists
    so two engines with the same engine ``name`` can still join one
    fleet under distinct replica names."""

    def __init__(self, engine, name: Optional[str] = None):
        self.engine = engine
        self.name = name or engine.name

    def submit(self, tokens, max_new_tokens: int = 16, **kw) -> Future:
        return self.engine.submit(tokens, max_new_tokens, **kw)

    def load_report(self) -> Dict[str, object]:
        return self.engine.load_report()

    def close(self) -> None:
        self.engine.close()


class ReplicaServer(_ps._Node):
    """Expose one engine over the ``ps.py`` framing (length-prefixed
    pickle on a persistent connection, the ``_ConnPool`` channel
    idiom).

    Every message carries a client-chosen ``rid``; every reply echoes
    it, so responses can arrive out of submission order — ``submit``
    replies are sent from the engine future's done-callback (the
    engine's loop thread) while the handler thread keeps reading.  All
    replies to one connection serialize through a per-connection write
    lock so frames never interleave."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0):
        super().__init__(host, port)
        self.engine = engine
        self.start()

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def close(self) -> None:
        self.stop()

    @staticmethod
    def _send_lock(handler) -> threading.Lock:
        # created by the handler thread before any callback can exist
        # for this connection, so there is a single racing creator
        lk = getattr(handler, "tp_wlock", None)
        if lk is None:
            lk = handler.tp_wlock = threading.Lock()
        return lk

    def _reply(self, handler, wlock, payload) -> None:
        try:
            with wlock:
                _ps._send_msg(handler.request, payload)
        except OSError:
            pass  # peer gone; its reader fails the waiters

    def _reply_result(self, handler, wlock, rid, fut,
                      trace_wire=None) -> None:
        exc = fut.exception()
        if exc is not None:
            self._reply(handler, wlock, {"status": "error", "rid": rid,
                                         "error": repr(exc)})
        else:
            r = fut.result()
            self._reply(handler, wlock, {
                "status": "ok", "rid": rid,
                "tokens": np.asarray(r.tokens, np.int32),
                "logits": r.logits, "prompt_len": int(r.prompt_len),
                "ttft_s": float(r.ttft_s)})
        if trace_wire is not None:
            # finalize the trace fragment this process adopted from
            # the wire (no-op when the trace is locally rooted — the
            # in-process fleet shares one recorder)
            tracing.finish_remote(trace_wire)

    def _handle(self, msg, handler):
        wlock = self._send_lock(handler)
        rid = None
        try:
            rid = msg.get("rid")
            cmd = msg.get("cmd")
            if cmd == "load_report":
                self._reply(handler, wlock, {
                    "status": "ok", "rid": rid,
                    "report": self.engine.load_report()})
            elif cmd == "submit":
                kw = msg.get("kw") or {}
                tw = kw.get("trace_ctx") if tracing.enabled() else None
                fut = self.engine.submit(
                    np.asarray(msg["tokens"], np.int32),
                    int(msg["max_new"]), **kw)
                fut.add_done_callback(
                    lambda f, r=rid, h=handler, w=wlock, t=tw:
                    self._reply_result(h, w, r, f, t))
            else:
                self._reply(handler, wlock, {
                    "status": "error", "rid": rid,
                    "error": "unknown cmd %r" % (cmd,)})
        except Exception as exc:  # noqa: BLE001 — shipped to the peer
            self._reply(handler, wlock, {"status": "error", "rid": rid,
                                         "error": repr(exc)})
        return _ps._NO_REPLY


def _relay_result(raw: Future, out: Future) -> None:
    """Map a raw wire-reply future onto a GenerationResult future."""
    if out.done():
        return
    exc = raw.exception()
    if exc is not None:
        out.set_exception(exc)
        return
    msg = raw.result()
    out.set_result(GenerationResult(
        np.asarray(msg["tokens"], np.int32), msg.get("logits"),
        int(msg["prompt_len"]), -1, float(msg["ttft_s"])))


@race_audit
class TcpReplica(Replica):
    """Client handle to a :class:`ReplicaServer`: one persistent
    socket (the ``_ConnPool`` idiom — no per-request connect churn), a
    write lock serializing outbound frames, and a reader thread
    dispatching replies to per-request futures by ``rid``.

    A broken connection fails every outstanding future and poisons the
    handle (``submit``/``load_report`` raise) — the router's heartbeat
    then marks the replica dead and re-routes; reconnection is a new
    ``TcpReplica``, not a hidden retry."""

    def __init__(self, addr: Tuple[str, int],
                 name: Optional[str] = None, *,
                 timeout: Optional[float] = None,
                 connect_retry: float = 5.0):
        self.addr = (str(addr[0]), int(addr[1]))
        self.name = name or "tcp://%s:%d" % self.addr
        self._timeout = float(
            timeout if timeout is not None
            else get_env("ROUTER_RPC_TIMEOUT", 60.0, float))
        self._sock = _ps._connect(self.addr, self._timeout,
                                  connect_retry)
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._next_rid = 0
        self._waiters: Dict[int, Future] = {}
        self._broken: Optional[BaseException] = None
        self._reader = threading.Thread(
            target=self._read_loop, name=self.name + "-reader",
            daemon=True)
        self._reader.start()

    # ------------------------------------------------------------ wire
    def _read_loop(self) -> None:
        while True:
            try:
                msg = _ps._recv_msg(self._sock)
            except (ConnectionError, OSError) as exc:
                self._fail_pending(exc)
                return
            with self._lock:
                fut = self._waiters.pop(msg.get("rid"), None)
            if fut is None or fut.done():
                continue
            if msg.get("status") == "ok":
                fut.set_result(msg)
            else:
                fut.set_exception(MXNetError(
                    "replica %s: %s" % (self.name, msg.get("error"))))

    def _fail_pending(self, exc: BaseException) -> None:
        with self._lock:
            if self._broken is None:
                self._broken = exc
            waiters, self._waiters = self._waiters, {}
        err = MXNetError("replica %s connection lost: %r"
                         % (self.name, exc))
        for fut in waiters.values():
            if not fut.done():
                fut.set_exception(err)

    def _call(self, msg: Dict[str, object]) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._broken is not None:
                raise MXNetError(
                    "replica %s connection lost: %r"
                    % (self.name, self._broken))
            self._next_rid += 1
            rid = self._next_rid
            self._waiters[rid] = fut
        msg["rid"] = rid
        try:
            with self._wlock:
                # bounded: the socket carries the connect timeout, so
                # sendall cannot stall past it
                _ps._send_msg(self._sock, msg)
        except (ConnectionError, OSError) as exc:
            with self._lock:
                self._waiters.pop(rid, None)
            self._fail_pending(exc)
            raise MXNetError("replica %s send failed: %r"
                             % (self.name, exc))
        return fut

    # ------------------------------------------------------------- api
    def submit(self, tokens, max_new_tokens: int = 16, **kw) -> Future:
        toks = np.asarray(tokens, np.int32).reshape(-1)
        tctx = tracing.from_wire(kw.get("trace_ctx")) \
            if "trace_ctx" in kw else None
        t_rpc = time.monotonic() if tctx is not None else 0.0
        raw = self._call({"cmd": "submit", "tokens": toks,
                          "max_new": int(max_new_tokens), "kw": kw})
        out: Future = Future()
        if tctx is not None:
            def _done(f, c=tctx, t0=t_rpc):
                # wire round-trip attribution: overlaps the replica's
                # queue/prefill/decode spans, so trace_query reports it
                # as an overlay, not a critical-path phase
                tracing.record(c, "serve.rpc", t0, time.monotonic(),
                               {"replica": self.name})
                _relay_result(f, out)
            raw.add_done_callback(_done)
        else:
            raw.add_done_callback(lambda f: _relay_result(f, out))
        return out

    def load_report(self) -> Dict[str, object]:
        reply = self._call({"cmd": "load_report"}).result(
            timeout=self._timeout)
        return reply["report"]

    def close(self) -> None:
        with self._lock:
            if self._broken is None:
                self._broken = MXNetError(
                    "replica %s closed" % self.name)
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# admission policy pieces
# ---------------------------------------------------------------------------


class TenantQuota:
    """Token bucket in LM tokens (prompt + max_new) per second.

    ``rate`` refills continuously up to ``burst`` (default:
    ``max(rate, 1)``); a request costing more than the current level
    is shed at admission.  Mutated only under the router lock."""

    __slots__ = ("rate", "burst", "level", "t")

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(self.rate, 1.0))
        self.level = self.burst
        self.t: Optional[float] = None

    def try_take(self, n: float, now: float) -> bool:
        if self.t is None:
            self.t = now
        self.level = min(self.burst,
                         self.level + (now - self.t) * self.rate)
        self.t = now
        if n <= self.level:
            self.level -= n
            return True
        return False


class _Placement:
    """One routed request's router-side record.  Every mutable field
    is guarded by the router lock; ``epoch`` invalidates done-callbacks
    of dispatches that were superseded by a re-route."""

    __slots__ = ("rid", "tokens", "max_new", "kw", "tenant", "klass",
                 "session", "retryable", "deadline", "chains", "tried",
                 "retries_left", "epoch", "state", "done", "last_exc",
                 "future", "t_submit", "trace")

    def __init__(self, rid, tokens, max_new, kw, tenant, klass,
                 session, retryable, deadline, chains, retries):
        self.rid = rid
        self.tokens = tokens
        self.max_new = max_new
        self.kw = kw
        self.tenant = tenant
        self.klass = klass
        self.session = session
        self.retryable = retryable
        self.deadline = deadline          # absolute monotonic or None
        self.chains = chains              # page_tokens -> digest chain
        self.tried: Set[str] = set()
        self.retries_left = retries
        self.epoch = 0
        self.state = None                 # current _ReplicaState
        self.done = False
        self.last_exc: Optional[BaseException] = None
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.trace = None                 # tracing.SpanContext or None


class _ReplicaState:
    """Router-side mirror of one replica: last load report, the prefix
    digest mirror, and the in-flight placements.  Guarded by the
    router lock (the replica handle itself is only ever called outside
    it)."""

    __slots__ = ("replica", "name", "alive", "draining", "report",
                 "last_ok", "misses", "placed", "digests", "inflight")

    def __init__(self, replica):
        self.replica = replica
        self.name = replica.name
        self.alive = True
        self.draining = False
        self.report: Optional[Dict[str, object]] = None
        self.last_ok = time.monotonic()
        self.misses = 0
        # placements routed since the last report: de-stales the
        # report's free_slots/queue_depth between heartbeats
        self.placed = 0
        self.digests: Set[bytes] = set()
        self.inflight: Dict[int, _Placement] = {}


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


@race_audit
class ServingRouter:
    """Thread-safe front door over N generation replicas.

    ``submit`` admits (quota, deadline feasibility), places (sticky
    session, then prefix score, then power-of-two-choices), dispatches
    to the chosen replica, and returns a Future resolving to that
    replica's :class:`~.generate.GenerationResult`.  Admission
    failures raise ``MXNetError`` synchronously — shedding happens
    before any prefill spend, never after.

    See the module docstring for the placement and failover contracts,
    and docs/fleet_serving.md for the knob and telemetry tables.
    """

    def __init__(self, replicas=(), *, policy: Optional[str] = None,
                 session_ttl_s: Optional[float] = None,
                 heartbeat_s: Optional[float] = None,
                 dead_after_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 slack: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 seed: int = 0, name: str = "fleet"):
        self.name = name
        self.policy = str(policy if policy is not None
                          else get_env("ROUTER_POLICY", "prefix"))
        if self.policy not in ("prefix", "p2c", "round_robin"):
            raise MXNetError(
                "TP_ROUTER_POLICY must be prefix|p2c|round_robin, "
                "got %r" % (self.policy,))
        self._session_ttl = float(
            session_ttl_s if session_ttl_s is not None
            else get_env("ROUTER_SESSION_TTL_S", 300.0, float))
        self._heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else get_env("ROUTER_HEARTBEAT_S", 1.0, float))
        self._dead_after_s = float(
            dead_after_s if dead_after_s is not None
            else get_env("ROUTER_DEAD_S", 5.0, float))
        self._retries = int(retries if retries is not None
                            else get_env("ROUTER_RETRIES", 1, int))
        self._slack = float(slack if slack is not None
                            else get_env("ROUTER_SLACK", 0.8, float))
        self._drain_timeout = float(
            drain_timeout_s if drain_timeout_s is not None
            else get_env("ROUTER_DRAIN_TIMEOUT_S", 120.0, float))
        self._class_slo = {
            "interactive": get_env("ROUTER_INTERACTIVE_SLO_MS", 0.0,
                                   float),
            "batch": get_env("ROUTER_BATCH_SLO_MS", 0.0, float),
        }
        self._lock = threading.Condition()
        self._replicas: Dict[str, _ReplicaState] = {}
        self._sessions: Dict[str, Tuple[str, float]] = {}
        self._buckets: Dict[str, TenantQuota] = {}
        self._rng = random.Random(seed)
        self._rr = 0
        self._next_rid = 0
        self._closed = False
        # host-side mirrors (tests/bench read without telemetry)
        self._n_requests = 0
        self._prefix_routed = 0
        self._retries_n = 0
        self._deaths = 0
        self._shed: Dict[str, int] = {}
        self._shed_by_class: Dict[str, int] = {}
        # per-deadline-class SLO attainment (settled requests only;
        # sheds are visible separately in shed_by_class)
        self._class_done: Dict[str, int] = {}
        self._class_met: Dict[str, int] = {}
        for r in replicas:
            self.attach(r)
        self._stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name=name + "-heartbeat",
            daemon=True)
        self._hb_thread.start()

    # ------------------------------------------------------------ membership
    def attach(self, replica) -> None:
        """Add a replica (anything satisfying the :class:`Replica`
        protocol; a bare engine works too) and probe it once so
        placement has a report before the first heartbeat."""
        st = _ReplicaState(replica)
        with self._lock:
            if self._closed:
                raise MXNetError("router %r is closed" % self.name)
            if st.name in self._replicas:
                raise MXNetError(
                    "replica name %r already attached — wrap it in "
                    "EngineReplica(engine, name=...) for a unique "
                    "name" % (st.name,))
            self._replicas[st.name] = st
        self._probe(st)

    def detach(self, replica) -> None:
        """Remove a replica immediately (no drain: its in-flight
        requests keep their state and settle normally)."""
        name = replica if isinstance(replica, str) else replica.name
        with self._lock:
            self._replicas.pop(name, None)
            for s in [s for s, (n, _) in self._sessions.items()
                      if n == name]:
                del self._sessions[s]

    @property
    def replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def session_replica(self, session: str) -> Optional[str]:
        """The replica a live session is pinned to (None once the TTL
        lapsed)."""
        with self._lock:
            ent = self._sessions.get(session)
            if ent is None or time.monotonic() >= ent[1]:
                return None
            return ent[0]

    def set_quota(self, tenant: str, rate: float,
                  burst: Optional[float] = None) -> None:
        """Install/replace a tenant's token bucket (LM tokens/s)."""
        with self._lock:
            self._buckets[tenant] = TenantQuota(rate, burst)

    # ------------------------------------------------------------- admission
    def submit(self, tokens, max_new_tokens: int = 16, *,
               tenant: str = "default", klass: str = "interactive",
               session: Optional[str] = None, retryable: bool = True,
               deadline_ms: Optional[float] = None, **kw) -> Future:
        """Admit, place, and dispatch one request.  Raises
        ``MXNetError`` synchronously when shed (quota exhausted, no
        replica can meet the deadline, or no replica can ever fit the
        request) — rejection always happens before prefill spend."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        if toks.size < 1:
            raise MXNetError("empty prompt")
        if klass not in DEADLINE_CLASSES:
            raise MXNetError("deadline class must be one of %s, got %r"
                             % (DEADLINE_CLASSES, klass))
        if deadline_ms is None:
            slo = self._class_slo[klass]
            deadline_ms = float(slo) if slo and slo > 0 else None
        now = time.monotonic()
        deadline = now + deadline_ms / 1e3 \
            if deadline_ms is not None else None
        # root span opened at admission so even shed requests leave a
        # (flagged, always-kept) trace; the attrs dict only allocates
        # on the enabled path
        trace = tracing.start_trace(
            "serve.request", {"tenant": tenant, "class": klass,
                              "prompt_tokens": int(toks.size),
                              "max_new": int(max_new_tokens)}) \
            if tracing.enabled() else None
        # digest chains per page size seen in the fleet, computed
        # OUTSIDE the lock (hashing is the expensive part of routing)
        with self._lock:
            sizes = {int((st.report or {}).get("page_tokens") or 0)
                     for st in self._replicas.values()}
        chains = {P: prefix_hashes(toks, P)
                  for P in sizes if P > 0}
        with self._lock:
            if self._closed:
                raise MXNetError("router %r is closed" % self.name)
            self._next_rid += 1
            rec = _Placement(self._next_rid, toks,
                             int(max_new_tokens), kw, tenant, klass,
                             session, retryable, deadline, chains,
                             self._retries)
            rec.trace = trace
            quota = self._buckets.get(tenant)
            if quota is not None and not quota.try_take(
                    toks.size + rec.max_new, now):
                self._shed_locked(rec, "quota",
                                  "tenant %r token bucket empty"
                                  % (tenant,))
            st = self._pick(rec, now)
            self._n_requests += 1
        telemetry.counter("fleet_requests_total",
                          {"tenant": tenant, "class": klass}).inc()
        if not self._dispatch_once(rec, st):
            self._route(rec)
        return rec.future

    def generate(self, tokens, max_new_tokens: int = 16,
                 timeout: Optional[float] = 120.0,
                 **kw) -> GenerationResult:
        """Synchronous convenience wrapper over :meth:`submit`."""
        return self.submit(tokens, max_new_tokens, **kw).result(
            timeout=timeout)

    # ------------------------------------------------------------- placement
    def _shed_locked(self, rec: _Placement, reason: str,
                     detail: str) -> None:
        """Count and raise an admission rejection (lock held)."""
        self._shed[reason] = self._shed.get(reason, 0) + 1
        self._shed_by_class[rec.klass] = \
            self._shed_by_class.get(rec.klass, 0) + 1
        telemetry.counter("fleet_shed_total",
                          {"reason": reason,
                           "class": rec.klass}).inc()
        if rec.trace is not None:
            # tail-sampling contract: shed traces are always kept
            tracing.flag(rec.trace, "shed")
            tracing.record(rec.trace, "router.shed", rec.t_submit,
                           time.monotonic(), {"reason": reason})
            tracing.end_trace(rec.trace)
        raise MXNetError(
            "fleet shed [%s] tenant=%r class=%r: %s"
            % (reason, rec.tenant, rec.klass, detail))

    def _fits(self, st: _ReplicaState, rec: _Placement) -> bool:
        """Could this replica EVER run the request (static capability,
        not current load)?"""
        r = st.report
        if r is None:
            return True  # not probed yet: optimistic
        if r.get("closed"):
            return False
        if rec.tokens.size + rec.max_new > int(r.get("max_len") or
                                               1 << 30):
            return False
        P = int(r.get("page_tokens") or 0)
        if P:
            need = -(-(rec.tokens.size + rec.max_new) // P)
            if need > int(r.get("total_pages") or need):
                return False
        return True

    def _load(self, st: _ReplicaState) -> float:
        r = st.report
        if r is None:
            return float(st.placed)
        slots = max(1, int(r.get("max_slots") or 1))
        return (int(r.get("active_slots") or 0)
                + int(r.get("queue_depth") or 0)
                + st.placed) / slots

    def _eta_ms(self, st: _ReplicaState) -> float:
        """Optimistic finish-time estimate: the engine's completed-
        request EWMA scaled by how many batch waves precede a new
        arrival.  Cold engines (EWMA 0) estimate 0 — admit and let
        measurements accumulate."""
        r = st.report
        if r is None:
            return 0.0
        est = float(r.get("est_request_s") or 0.0) * 1e3
        free = int(r.get("free_slots") or 0) - st.placed
        if free > 0:
            return est
        q = int(r.get("queue_depth") or 0) + st.placed
        slots = max(1, int(r.get("max_slots") or 1))
        return est * (q // slots + 2)

    def _sticky(self, rec: _Placement, fits: List[_ReplicaState],
                now: float) -> Optional[_ReplicaState]:
        if rec.session is None:
            return None
        ent = self._sessions.get(rec.session)
        if ent is None:
            return None
        name, expiry = ent
        if now >= expiry:
            del self._sessions[rec.session]
            return None
        for st in fits:
            if st.name == name:
                return st
        return None

    def _best_prefix(self, fits: List[_ReplicaState], rec: _Placement,
                     ) -> Tuple[Optional[_ReplicaState], int]:
        """Longest-cached-prefix scoring: leading digests of the
        request's chain present in the replica's mirror, in tokens.
        Only FULL pages strictly before the last prompt token count —
        the same shareability rule the paged admission applies."""
        best, best_tokens, best_load = None, 0, 0.0
        for st in fits:
            P = int((st.report or {}).get("page_tokens") or 0)
            chain = rec.chains.get(P)
            if not P or not chain:
                continue
            share = (rec.tokens.size - 1) // P
            n = 0
            for d in chain[:share]:
                if d not in st.digests:
                    break
                n += 1
            tokens = n * P
            if tokens == 0:
                continue
            load = self._load(st)
            if tokens > best_tokens or (tokens == best_tokens
                                        and load < best_load):
                best, best_tokens, best_load = st, tokens, load
        return best, best_tokens

    def _fallback(self, fits: List[_ReplicaState]) -> _ReplicaState:
        if len(fits) == 1:
            return fits[0]
        if self.policy == "round_robin":
            self._rr += 1
            return fits[self._rr % len(fits)]
        a, b = self._rng.sample(fits, 2)  # power of two choices
        return a if self._load(a) <= self._load(b) else b

    def _pick(self, rec: _Placement, now: float,
              exclude=()) -> _ReplicaState:
        """Choose a replica and record the placement (lock held).
        Raises via :meth:`_shed_locked` when nothing can take the
        request."""
        live = [st for st in self._replicas.values()
                if st.alive and not st.draining
                and st.name not in exclude]
        if not live:
            self._shed_locked(rec, "capacity", "no live replica")
        fits = [st for st in live if self._fits(st, rec)]
        if not fits:
            self._shed_locked(
                rec, "capacity",
                "request (%d prompt + %d new tokens) exceeds every "
                "replica's budget" % (rec.tokens.size, rec.max_new))
        if rec.deadline is not None:
            budget = max(0.0, (rec.deadline - now) * 1e3) * self._slack
            ok = [st for st in fits if self._eta_ms(st) <= budget]
            if not ok:
                self._shed_locked(
                    rec, "deadline",
                    "no replica can finish inside %.0f ms"
                    % ((rec.deadline - now) * 1e3))
            fits = ok
        hit_tokens = 0
        st = self._sticky(rec, fits, now)
        if st is None and self.policy == "prefix":
            st, hit_tokens = self._best_prefix(fits, rec)
        if st is None:
            st = self._fallback(fits)
        rec.epoch += 1
        rec.state = st
        st.inflight[rec.rid] = rec
        st.placed += 1
        P = int((st.report or {}).get("page_tokens") or 0)
        if P and rec.chains.get(P):
            # optimistic mirror: the pages this prompt will register
            st.digests.update(rec.chains[P])
        if rec.session is not None:
            self._sessions[rec.session] = (st.name,
                                           now + self._session_ttl)
        if hit_tokens:
            self._prefix_routed += 1
            telemetry.counter("fleet_routed_prefix_hits_total").inc()
            telemetry.counter("fleet_prefix_hit_tokens_total").inc(
                hit_tokens)
        return st

    # -------------------------------------------------------------- dispatch
    def _unplace(self, rec: _Placement, st: _ReplicaState) -> None:
        with self._lock:
            st.inflight.pop(rec.rid, None)
            self._lock.notify_all()

    def _dispatch_once(self, rec: _Placement,
                       st: _ReplicaState) -> bool:
        """Hand a recorded placement to its replica.  Returns False
        when the replica rejected synchronously (backpressure, closed)
        and the caller should re-pick elsewhere; True when dispatched
        OR terminally settled."""
        now = time.monotonic()
        kw = dict(rec.kw)
        if rec.deadline is not None:
            remaining = (rec.deadline - now) * 1e3
            if remaining <= 0:
                self._unplace(rec, st)
                self._settle(rec, exc=MXNetError(
                    "deadline expired before dispatch (%.1f ms in "
                    "router)" % ((now - rec.t_submit) * 1e3)))
                return True
            # the engine enforces the REMAINING budget queue-side
            kw["deadline_ms"] = remaining
        with self._lock:
            epoch = rec.epoch
        if rec.trace is not None:
            # context rides the existing kw dict through the replica
            # protocol (and the ps.py framing, for TCP replicas)
            kw["trace_ctx"] = rec.trace.to_wire()
            if epoch == 1:
                # admission span: validation + quota + placement cost
                tracing.record(rec.trace, "router.admit",
                               rec.t_submit, now,
                               {"replica": st.name})
        try:
            efut = st.replica.submit(rec.tokens, rec.max_new, **kw)
        except Exception as exc:  # noqa: BLE001 — re-picked/settled
            self._unplace(rec, st)
            with self._lock:
                rec.tried.add(st.name)
                rec.last_exc = exc
            return False
        efut.add_done_callback(
            lambda f, r=rec, e=epoch: self._on_done(r, e, f))
        return True

    def _route(self, rec: _Placement) -> None:
        """Re-pick and dispatch until placed or out of candidates
        (used after dispatch-time rejections and for failover
        re-routes; failures settle the future, they never raise)."""
        while True:
            try:
                with self._lock:
                    st = self._pick(rec, time.monotonic(),
                                    exclude=rec.tried)
            except MXNetError as exc:
                self._settle(rec, exc=rec.last_exc or exc)
                return
            if self._dispatch_once(rec, st):
                return

    def _on_done(self, rec: _Placement, epoch: int,
                 efut: Future) -> None:
        """Engine-future completion (runs on the replica's loop or
        reader thread).  Success settles the router future (first
        settle wins — a late success from a superseded dispatch is
        still a valid greedy result).  Failure retries on another
        replica when the request is retryable and the failure belongs
        to the current dispatch epoch."""
        exc = efut.exception()
        if exc is None:
            self._settle(rec, result=efut.result())
            return
        retry = False
        with self._lock:
            if rec.done or rec.epoch != epoch:
                return
            st = rec.state
            if st is not None:
                st.inflight.pop(rec.rid, None)
                rec.state = None
                self._lock.notify_all()
            if rec.retryable and rec.retries_left > 0 \
                    and not self._closed:
                rec.retries_left -= 1
                if st is not None:
                    rec.tried.add(st.name)
                rec.last_exc = exc
                self._retries_n += 1
                retry = True
        if not retry:
            self._settle(rec, exc=exc)
            return
        telemetry.counter("fleet_retries_total").inc()
        self._route(rec)

    def _settle(self, rec: _Placement, result=None, exc=None) -> None:
        """Resolve the router future exactly once and release the
        in-flight record (drain waiters are notified)."""
        now = time.monotonic()
        met = exc is None and (rec.deadline is None
                               or now <= rec.deadline)
        with self._lock:
            if rec.done:
                return
            rec.done = True
            st = rec.state
            if st is not None:
                st.inflight.pop(rec.rid, None)
                rec.state = None
            self._class_done[rec.klass] = \
                self._class_done.get(rec.klass, 0) + 1
            if met:
                self._class_met[rec.klass] = \
                    self._class_met.get(rec.klass, 0) + 1
            done_n = self._class_done[rec.klass]
            met_n = self._class_met.get(rec.klass, 0)
            self._lock.notify_all()
        lab = {"class": rec.klass}
        telemetry.histogram("fleet_request_seconds", lab).observe(
            now - rec.t_submit)
        telemetry.gauge("fleet_slo_attainment", lab).set(
            met_n / done_n)
        if rec.trace is not None:
            # tail flags: errored / deadline-busting traces always kept
            if exc is not None:
                tracing.flag(rec.trace, "error")
            if rec.deadline is not None and now > rec.deadline:
                tracing.flag(rec.trace, "deadline")
            tracing.end_trace(rec.trace)
        if exc is None:
            rec.future.set_result(result)
        else:
            rec.future.set_exception(exc)

    # --------------------------------------------------------------- health
    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._heartbeat_s):
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — heartbeat must survive
                continue

    def _probe(self, st: _ReplicaState) -> None:
        """One load_report round-trip for one replica (no lock held
        across the call)."""
        try:
            report = st.replica.load_report()
        except Exception as exc:  # noqa: BLE001 — counted as a miss
            self._redispatch(self._note_miss(st, exc,
                                             time.monotonic()))
            return
        self._redispatch(self._apply_report(st, report))

    def poll(self) -> None:
        """One synchronous heartbeat sweep over every replica —
        exactly what the background thread runs each interval, exposed
        so tests and drains can refresh the mirrors deterministically.
        """
        now = time.monotonic()
        with self._lock:
            states = list(self._replicas.values())
            for s in [s for s, (_, exp) in self._sessions.items()
                      if now >= exp]:
                del self._sessions[s]
        for st in states:
            self._probe(st)
        with self._lock:
            alive = sum(1 for s in self._replicas.values() if s.alive)
        telemetry.gauge("fleet_replicas_alive").set(alive)

    def _note_miss(self, st: _ReplicaState, exc: BaseException,
                   now: float) -> List[_Placement]:
        with self._lock:
            st.misses += 1
            if st.alive and now - st.last_ok > self._dead_after_s:
                return self._mark_dead_locked(
                    st, "no heartbeat for %.1f s (last error: %r)"
                    % (now - st.last_ok, exc), reroute=True)
        return []

    def _apply_report(self, st: _ReplicaState,
                      report: Dict[str, object]) -> List[_Placement]:
        with self._lock:
            st.report = report
            st.last_ok = time.monotonic()
            st.misses = 0
            st.placed = 0
            digests = set(report.get("prefix_digests") or ())
            P = int(report.get("page_tokens") or 0)
            if P:
                # keep the optimistic entries of still-in-flight
                # prompts: they register their pages on completion
                for rec in st.inflight.values():
                    chain = rec.chains.get(P)
                    if chain:
                        digests.update(chain)
            st.digests = digests
            if report.get("closed") and st.alive:
                # a closed engine drains its active slots, so the
                # in-flight futures still resolve — stop placements
                # but do not re-route what it will finish itself
                return self._mark_dead_locked(st, "engine closed",
                                              reroute=False)
        return []

    def _mark_dead_locked(self, st: _ReplicaState, why: str,
                          reroute: bool) -> List[_Placement]:
        """Mark a replica dead (lock held).  Returns the in-flight
        placements to fail/re-route OUTSIDE the lock."""
        st.alive = False
        self._deaths += 1
        telemetry.counter("fleet_replica_dead_total").inc()
        for s in [s for s, (n, _) in self._sessions.items()
                  if n == st.name]:
            del self._sessions[s]
        if not reroute:
            return []
        recs = list(st.inflight.values())
        st.inflight.clear()
        err = MXNetError("replica %r marked dead: %s"
                         % (st.name, why))
        for rec in recs:
            rec.epoch += 1   # invalidate the dead dispatch's callback
            rec.state = None
            rec.tried.add(st.name)
            rec.last_exc = err
        self._lock.notify_all()
        return recs

    def _redispatch(self, recs: List[_Placement]) -> None:
        """Fail-fast or re-route the in-flight of a dead replica."""
        for rec in recs:
            retry = False
            with self._lock:
                if rec.done:
                    continue
                if rec.retryable and rec.retries_left > 0 \
                        and not self._closed:
                    rec.retries_left -= 1
                    self._retries_n += 1
                    retry = True
            if not retry:
                self._settle(rec, exc=rec.last_exc)
                continue
            telemetry.counter("fleet_retries_total").inc()
            self._route(rec)

    # -------------------------------------------------------------- draining
    def drain(self, replica, timeout: Optional[float] = None) -> float:
        """Stop new placements on one replica, wait for its in-flight
        requests to settle, then detach it.  Returns the wall seconds
        the drain took; raises ``MXNetError`` on timeout (the replica
        stays attached and draining, so a later drain can finish the
        job).  The replica object itself is NOT closed — that is the
        caller's deploy step."""
        timeout = float(timeout if timeout is not None
                        else self._drain_timeout)
        name = replica if isinstance(replica, str) else replica.name
        t0 = time.monotonic()
        deadline = t0 + timeout
        with self._lock:
            st = self._replicas.get(name)
            if st is None:
                raise MXNetError("unknown replica %r" % (name,))
            st.draining = True
            for s in [s for s, (n, _) in self._sessions.items()
                      if n == name]:
                del self._sessions[s]
            while st.inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise MXNetError(
                        "drain of %r timed out after %.1f s with %d "
                        "request(s) in flight"
                        % (name, timeout, len(st.inflight)))
                self._lock.wait(timeout=min(left, 0.1))
            del self._replicas[name]
        dur = time.monotonic() - t0
        telemetry.histogram("fleet_drain_seconds").observe(dur)
        return dur

    # ------------------------------------------------------------ lifecycle
    def describe(self) -> Dict[str, object]:
        """One consistent snapshot of the router mirrors (tests and
        the bench read this instead of poking internals)."""
        with self._lock:
            return {
                "replicas": {st.name: {
                    "alive": st.alive,
                    "draining": st.draining,
                    "inflight": len(st.inflight),
                    "digests": len(st.digests),
                    "placed_since_report": st.placed,
                    "report": dict(st.report) if st.report else None,
                } for st in self._replicas.values()},
                "sessions": len(self._sessions),
                "requests": self._n_requests,
                "prefix_routed": self._prefix_routed,
                "retries": self._retries_n,
                "deaths": self._deaths,
                "shed": dict(self._shed),
                "shed_by_class": dict(self._shed_by_class),
                "slo_attainment": {
                    k: self._class_met.get(k, 0) / n
                    for k, n in self._class_done.items() if n},
            }

    def close(self, close_replicas: bool = False) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            states = list(self._replicas.values())
        self._stop.set()
        self._hb_thread.join(timeout=10)
        if close_replicas:
            for st in states:
                try:
                    st.replica.close()
                except Exception:  # noqa: BLE001 — best effort
                    continue

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
