"""Serving subsystem: bucketed dynamic batching (:mod:`.engine`),
KV-cache continuous-batching generation (:mod:`.generate`), the paged
KV cache with prefix caching (:mod:`.paged`), speculative decoding
with chunked prefill (:mod:`.speculative`), and the fleet router over
N replicas (:mod:`.router`).

See docs/serving.md, docs/paged_kv.md, docs/speculative_decoding.md
and docs/fleet_serving.md for the architecture and knob tables."""
from .engine import InferenceEngine, bucket_batch, bucket_length
from .generate import (GenerationEngine, GenerationResult,
                       KVTransformerLM, LMSpec)
from .paged import (BlockPool, PagedGenerationEngine, PagedKVCache,
                    prefix_hashes)
from .router import (EngineReplica, Replica, ReplicaServer,
                     ServingRouter, TcpReplica, TenantQuota)
from .speculative import (DraftModel, PagedSpeculativeGenerationEngine,
                          SpeculativeGenerationEngine)

__all__ = ["InferenceEngine", "GenerationEngine", "GenerationResult",
           "KVTransformerLM", "LMSpec", "BlockPool", "PagedKVCache",
           "PagedGenerationEngine", "prefix_hashes", "bucket_batch",
           "bucket_length", "DraftModel", "SpeculativeGenerationEngine",
           "PagedSpeculativeGenerationEngine", "Replica",
           "EngineReplica", "TcpReplica", "ReplicaServer",
           "TenantQuota", "ServingRouter"]
