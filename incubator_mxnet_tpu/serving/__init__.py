"""Serving subsystem: bucketed dynamic batching (:mod:`.engine`) and
KV-cache continuous-batching generation (:mod:`.generate`).

See docs/serving.md for the architecture and knob table."""
from .engine import InferenceEngine, bucket_batch, bucket_length
from .generate import (GenerationEngine, GenerationResult,
                       KVTransformerLM, LMSpec)

__all__ = ["InferenceEngine", "GenerationEngine", "GenerationResult",
           "KVTransformerLM", "LMSpec", "bucket_batch", "bucket_length"]
