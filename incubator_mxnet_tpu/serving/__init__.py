"""Serving subsystem: bucketed dynamic batching (:mod:`.engine`),
KV-cache continuous-batching generation (:mod:`.generate`), and the
paged KV cache with prefix caching (:mod:`.paged`).

See docs/serving.md and docs/paged_kv.md for the architecture and knob
tables."""
from .engine import InferenceEngine, bucket_batch, bucket_length
from .generate import (GenerationEngine, GenerationResult,
                       KVTransformerLM, LMSpec)
from .paged import (BlockPool, PagedGenerationEngine, PagedKVCache,
                    prefix_hashes)

__all__ = ["InferenceEngine", "GenerationEngine", "GenerationResult",
           "KVTransformerLM", "LMSpec", "BlockPool", "PagedKVCache",
           "PagedGenerationEngine", "prefix_hashes", "bucket_batch",
           "bucket_length"]
