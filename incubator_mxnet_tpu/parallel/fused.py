"""FusedTrainStep — forward+backward+optimizer as ONE sharded XLA program.

This is the TPU-native replacement for the reference's per-batch sequence
{executor forward, executor backward, kvstore push/pull, optimizer update}
(SURVEY.md §3.1): under ``jax.jit`` over a ``Mesh``, XLA fuses the whole
step and inserts the gradient all-reduce (psum over the ``dp`` axis) where
the KVStore push/pull used to be — overlapping it with backward compute the
way the reference overlapped ps-lite ZPush with backprop via engine
priorities (``kvstore_dist.h`` negative-key priorities).

Params/optimizer-states/aux live donated on-device; the learning rate is a
dynamic scalar input so schedules don't retrigger compilation.

``shard_optimizer=True`` adds ZeRO-1 optimizer-state sharding
(``parallel/zero.py``, reference analog: per-server key-range updates in
``kvstore_dist_server.h:105-230``): each param's m/v/momentum live split
over the dp (and ep) axes, gradients reduce-scatter into the owned
shard, the update runs shard-local, and updated params all-gather back —
the per-device state footprint drops to ~1/dp of the replicated layout.
"""
from __future__ import annotations

import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry, tracing
from ..base import MXNetError, dtype_np, get_env
from ..ops.registry import OpContext, get_op
from .mesh import (data_parallel_spec, default_mesh, replicated_spec)

__all__ = ["FusedTrainStep"]


# optimizer name → (update op, #states); the resolution itself
# (momentum-dispatched sgd included) lives in optimizer.fused_update_plan
from ..optimizer import FUSED_UPDATE_OPS as _FUSED_OPTS
from ..optimizer import fused_update_plan as _fused_update_plan


from ..lowering import lower_symbol as _lower_symbol  # shared lowering


def _fill_rule(v):
    def rule(key, shape):
        import jax.numpy as jnp

        return jnp.full(shape, v, jnp.float32)

    return rule


def _weight_rule(initializer, shape):
    """Device-side generator for one weight under ``initializer``, or
    None when the (exact) class is not a recognized built-in — a
    subclass may override ``_init_weight`` arbitrarily and must take
    the host path."""
    import jax
    import jax.numpy as jnp

    from ..initializer import (Constant, MSRAPrelu, Normal, One, Uniform,
                               Xavier, Zero)

    init_t = type(initializer)
    if init_t is Uniform:
        s = float(initializer.scale)
        return lambda key, sh: jax.random.uniform(
            key, sh, jnp.float32, -s, s)
    if init_t is Normal:
        s = float(initializer.sigma)
        return lambda key, sh: s * jax.random.normal(
            key, sh, jnp.float32)
    if init_t is Zero:
        return _fill_rule(0.0)
    if init_t is One:
        return _fill_rule(1.0)
    if init_t is Constant:
        return _fill_rule(float(initializer.value))
    if init_t in (Xavier, MSRAPrelu):
        # scale is a static function of the shape — THE shared
        # Xavier.weight_scale, so host/device cannot drift
        scale = initializer.weight_scale(shape)
        if initializer.rnd_type == "uniform":
            return lambda key, sh: jax.random.uniform(
                key, sh, jnp.float32, -scale, scale)
        return lambda key, sh: scale * jax.random.normal(
            key, sh, jnp.float32)
    return None


def _device_init_plan(initializer, param_names):
    """name → device-side generator ``fn(key, shape) -> jnp array``
    for every param, or None when any param needs the host fallback.

    The generator set mirrors ``Initializer.__call__``'s dispatch: a
    per-variable ``__init__`` attr wins outright (reference InitDesc
    semantics), then the name patterns (bias→0, gamma→1, …), then the
    weight rule of the global initializer.  Device-side init matters
    on a tunneled chip: it replaces the H2D upload of every master
    weight (minutes when tunnel weather degrades, PERF.md §1) with one
    jitted on-chip program.  ``param_names`` entries are
    ``(name, shape)`` or ``(name, shape, attrs)``."""
    from ..initializer import create as _create_init

    plan = {}
    for entry in param_names:
        n, shape = entry[0], entry[1]
        attrs = entry[2] if len(entry) > 2 else None
        init_attr = (attrs or {}).get("__init__")
        if init_attr:
            try:
                sub = _create_init(init_attr)
            except Exception:
                return None
            rule = _weight_rule(sub, shape)
            if rule is None:
                return None
            plan[n] = rule
            continue
        name = n.lower()
        if name.endswith("upsampling"):
            return None  # Bilinear kernels stay on the host path
        if name.endswith(("bias", "beta", "moving_mean", "running_mean",
                          "moving_inv_var", "moving_avg")):
            plan[n] = _fill_rule(0.0)
        elif name.endswith(("gamma", "moving_var", "running_var")):
            plan[n] = _fill_rule(1.0)
        else:
            rule = _weight_rule(initializer, shape)
            if rule is None:
                return None
            plan[n] = rule
    return plan


class _HostInitBuffer:
    """numpy-backed stand-in handed to initializers at setup time.

    Every built-in initializer only reads ``.shape`` and assigns
    ``arr[:] = <numpy or scalar>``, so param init never needs to touch
    the device; see the host_init comment for why that matters on a
    tunneled chip."""

    __slots__ = ("_np",)

    def __init__(self, shape):
        self._np = np.zeros(shape, np.float32)

    @property
    def shape(self):
        return self._np.shape

    def __setitem__(self, key, value):
        self._np[key] = value.asnumpy() \
            if hasattr(value, "asnumpy") else value

    def asnumpy(self):
        return self._np


class FusedTrainStep:
    """One-program data-parallel trainer over a mesh.

    >>> step = FusedTrainStep(net, {'data': (256, 3, 224, 224)},
    ...                       {'softmax_label': (256,)}, mesh=mesh,
    ...                       optimizer='sgd',
    ...                       optimizer_params={'momentum': 0.9})
    >>> out = step(batch)          # params update in place (donated)
    """

    def __init__(self, symbol, data_shapes: Dict[str, Sequence[int]],
                 label_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 mesh=None, optimizer: str = "sgd",
                 optimizer_params: Optional[Dict[str, Any]] = None,
                 initializer=None, dtype=None, seed: int = 0,
                 param_partition: Optional[Dict[str, Any]] = None,
                 flat_optimizer: bool = False, remat=None,
                 grad_accum: Optional[int] = None,
                 opt_state_dtype=None, grad_dtype=None,
                 shard_optimizer: Optional[bool] = None,
                 metrics=None, matmul_dtype=None,
                 grad_bucket_mb: Optional[float] = None,
                 grad_comm_dtype=None):
        import jax
        import jax.numpy as jnp

        self.symbol = symbol
        # recompute policy (MXNET_BACKWARD_DO_MIRROR parity): None reads
        # the TP_BACKWARD_DO_MIRROR / TP_REMAT_SEGMENTS env contract,
        # 'mirror' saves only matmul/conv outputs, int K checkpoints K
        # uniform graph segments (lowering.resolve_remat)
        self.remat = remat
        # gradient accumulation: k sequential microbatches inside the
        # ONE jitted step (lax.scan), summed grads, one optimizer
        # update.  Activation memory ~ batch/k; BN moving stats thread
        # sequentially through the scan.  The TP_GRAD_ACCUM env applies
        # only when the caller did not specify — an explicit value
        # (including 1 = off) always wins.
        if grad_accum is None:
            grad_accum = int(get_env("GRAD_ACCUM", 1, int))
        self._accum = int(grad_accum)
        if self._accum < 1:
            raise MXNetError("grad_accum must be >= 1")
        # optimizer-state storage dtype (e.g. "bfloat16"): halves the
        # m/v HBM streams of the update — the adam floor lever measured
        # in PERF.md §21.  Update math stays f32 (states upcast in the
        # step, downcast on store); opt-in, None = f32 masters.
        self._state_dtype = dtype_np(opt_state_dtype) \
            if opt_state_dtype else None
        # gradient storage/exchange dtype (e.g. "bfloat16"): the grads
        # leaving the backward are cast BEFORE accumulation and the dp
        # reduction, so cross-tick accumulators and the all-reduce move
        # half the bytes (comm-compression lever, SURVEY §5.8; the
        # remaining headroom named by round-4 verdict #5).  Update math
        # still upcasts to the master dtype; opt-in, None = f32.
        self._grad_dtype = dtype_np(grad_dtype) if grad_dtype else None
        # gradient bucketing + comms overlap (parallel/buckets.py,
        # docs/comm_overlap.md): >0 groups the grad pytree into
        # ~MB-sized buckets in backward-completion order and pins one
        # collective group per bucket, so the dp all-reduce / ZeRO
        # reduce-scatter overlaps the remaining backward compute; the
        # optional wire dtype (bf16) halves the bytes on the wire.
        # 0 (default) keeps the seed's monolithic reduction untouched.
        # The TP_GRAD_BUCKET_MB / TP_GRAD_COMM_DTYPE envs apply only
        # when the caller did not specify.
        from .buckets import resolve_comm_knobs

        self._bucket_mb, self._comm_dtype = resolve_comm_knobs(
            grad_bucket_mb, grad_comm_dtype)
        # fp8 matmul path (docs/quantization.md): every FullyConnected
        # matmul runs through quant.scaled_dot — e4m3 fwd / e5m2 bwd
        # casts with delayed per-tensor amax scaling; masters, grads
        # leaving the matmul, and the optimizer stay exactly as above.
        # The TP_MATMUL_DTYPE env applies only when the caller did not
        # specify; unset keeps the default path bit-identical.
        if matmul_dtype is None:
            matmul_dtype = get_env("MATMUL_DTYPE") or None
        if matmul_dtype in ("float32", "f32"):
            matmul_dtype = None
        if matmul_dtype not in (None, "fp8"):
            raise MXNetError(
                "matmul_dtype must be None or 'fp8', got %r"
                % (matmul_dtype,))
        self._matmul_dtype = matmul_dtype
        self._quant_recipe = None
        self._quant_sites = 0
        self.quant_state: Tuple = ()
        if self._matmul_dtype == "fp8":
            from .. import quant
            from ..lowering import resolve_remat

            if resolve_remat(self.remat) is not None:
                raise MXNetError(
                    "matmul_dtype='fp8' does not compose with remat: "
                    "jax.checkpoint replays the forward trace in the "
                    "backward, which would double-count the amax sites")
            self._quant_sites = sum(
                1 for node in symbol.topo_nodes()
                if not node.is_variable
                and node.op.name == "FullyConnected")
            if self._quant_sites == 0:
                raise MXNetError(
                    "matmul_dtype='fp8': the graph has no FullyConnected "
                    "sites to quantize")
            self._quant_recipe = quant.Recipe()
        self.mesh = mesh if mesh is not None else default_mesh()
        label_shapes = label_shapes or {}
        shapes = dict(data_shapes)
        shapes.update(label_shapes)
        self.input_names = list(shapes.keys())

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shapes)
        # full-batch output shapes: the grad_accum restack oracle
        self._out_shapes = [tuple(s) for s in out_shapes]
        self.param_names = [n for n in arg_names if n not in shapes]
        shape_of = dict(zip(arg_names, arg_shapes))
        self.global_batch = shapes[self.input_names[0]][0]
        if self._accum > 1:
            if self.global_batch % self._accum:
                raise MXNetError(
                    "global batch %d does not divide into %d "
                    "accumulation microbatches"
                    % (self.global_batch, self._accum))
            # microbatching slices axis 0 of EVERY input — a non-batch-
            # major input (e.g. time-major (T, N) sequences) would be
            # silently garbled, so require batch-major throughout
            for n, s in shapes.items():
                if not s or s[0] != self.global_batch:
                    raise MXNetError(
                        "grad_accum requires batch-major inputs; %r has "
                        "leading dim %s != global batch %d"
                        % (n, s[0] if s else None, self.global_batch))
            # loss heads normalize per MICROBATCH: any op with
            # normalization='batch'/'valid' (SoftmaxOutput, MakeLoss,
            # SoftmaxXentHead) divides its backward by the microbatch
            # count, so the k summed grads come out k-fold larger than
            # the same global batch un-accumulated (only 'null' is
            # accumulation-invariant) — reject rather than silently
            # train at k× the intended lr
            for node in symbol.topo_nodes():
                if node.op is None:
                    continue
                norm = (node.attrs or {}).get("normalization", "null")
                if norm != "null":
                    raise MXNetError(
                        "grad_accum=%d with op %s using "
                        "normalization=%r: the loss divides by the "
                        "microbatch (not global-batch) count, so "
                        "accumulated grads would be %d-fold too "
                        "large. Use normalization='null' with an "
                        "explicit grad_scale."
                        % (self._accum, node.op.name, norm,
                           self._accum))

        # ---- optimizer resolution ---------------------------------------
        opt_params = dict(optimizer_params or {})
        self.lr = float(opt_params.pop("learning_rate", 0.01))
        self.lr_scheduler = opt_params.pop("lr_scheduler", None)
        plan_upd = _fused_update_plan(optimizer, opt_params)
        if plan_upd is None:
            raise MXNetError("FusedTrainStep does not support optimizer %s"
                             % optimizer)
        self._opt_op, self._n_states = plan_upd
        opt_params.setdefault("rescale_grad", 1.0 / self.global_batch)
        self._opt_attrs = opt_params
        # flat mode: one fused update over the concatenation of every
        # parameter instead of one small op per parameter (measured 38%
        # SLOWER on ResNet-50 — PERF.md §7 — kept as an option).  Invalid
        # with per-param partition specs: a flat 1-D buffer has no
        # per-tensor sharding.
        if flat_optimizer and param_partition:
            raise MXNetError("flat_optimizer is incompatible with "
                             "param_partition (no per-tensor sharding on "
                             "a flat buffer)")
        if flat_optimizer and opt_state_dtype:
            raise MXNetError("flat_optimizer is incompatible with "
                             "opt_state_dtype")
        # the flat update consumes ONE concatenated grad buffer; feeding
        # it per-bucket collective outputs changes its fusion shapes,
        # which breaks the bucketed path's bit-equality contract
        # (docs/comm_overlap.md) — reject rather than silently drift
        if flat_optimizer and self._bucket_mb:
            raise MXNetError("flat_optimizer is incompatible with "
                             "grad bucketing (grad_bucket_mb / "
                             "TP_GRAD_BUCKET_MB)")
        self._flat_opt = bool(flat_optimizer)
        # ZeRO-1 optimizer-state sharding (parallel/zero.py): each
        # param's state lives split over the dp (and, composing with
        # expert sharding, ep) mesh axes.  The TP_SHARD_OPTIMIZER env
        # applies only when the caller did not specify.
        if shard_optimizer is None:
            shard_optimizer = bool(get_env("SHARD_OPTIMIZER", 0, int))
        if shard_optimizer and flat_optimizer:
            raise MXNetError("flat_optimizer is incompatible with "
                             "shard_optimizer (the flat 1-D buffer has "
                             "no per-tensor state sharding)")
        # plain sgd holds no state — nothing to shard
        self._zero = bool(shard_optimizer) and self._n_states > 0
        self.num_update = 0

        # ---- parameter init (host, then shard) --------------------------
        from ..initializer import InitDesc, Uniform

        initializer = initializer or Uniform(0.01)
        rep = replicated_spec(self.mesh)
        cast = dtype_np(dtype) if dtype else None
        # per-param sharding override: name → PartitionSpec (tensor/model
        # parallelism — the mesh_group analog of the reference's group2ctx)
        self._param_sharding = {}
        for n in self.param_names:
            spec = (param_partition or {}).get(n)
            if spec is not None:
                self._param_sharding[n] = jax.sharding.NamedSharding(
                    self.mesh, spec)
            else:
                self._param_sharding[n] = rep

        # optimizer-state shardings: the param's own placement, plus —
        # under ZeRO — the dp/ep axes folded onto the first divisible
        # free dim (zero_state_spec).  Params with no shardable dim
        # (scalars, nothing divisible) keep replicated state.
        from .zero import zero_state_spec

        self._state_sharding = dict(self._param_sharding)
        self._zero_names = set()
        if self._zero:
            mesh_axes = dict(self.mesh.shape)
            for n in self.param_names:
                zspec = zero_state_spec(
                    mesh_axes, (param_partition or {}).get(n),
                    tuple(shape_of[n]), shard_axes=("dp", "ep"))
                if zspec is not None:
                    self._state_sharding[n] = jax.sharding.NamedSharding(
                        self.mesh, zspec)
                    self._zero_names.add(n)

        # static bucket plan (built even at bucket_mb=0 so bench /
        # dryrun always have the byte + overlap report; the monolithic
        # single bucket is reporting-only and the step keeps the
        # unbucketed graph)
        from .buckets import build_plan, param_backward_order

        wire = self._comm_dtype or self._grad_dtype \
            or np.dtype(np.float32)
        order = param_backward_order(symbol, self.param_names)
        items = [(n, int(np.prod(shape_of[n])) if shape_of[n] else 1)
                 for n in order]
        self._bucket_plan = build_plan(
            items, self._bucket_mb, wire,
            "reduce_scatter" if self._zero else "all_reduce")
        self._bucket_plan.publish("fused")

        var_attrs = {node.name: (node.attrs or {})
                     for node in symbol.topo_nodes() if node.is_variable}

        def host_init(name, shape):
            # mixed precision: params stay f32 masters; ops cast to the
            # activation dtype at use sites (`cast` forces storage dtype
            # only when explicitly requested).  Init stays ENTIRELY on
            # host numpy: an on-device scratch would compile a program
            # per unique shape over the tunnel, and device_put of a
            # device-resident array round-trips through the ~5 MB/s D2H
            # path (PERF.md §1) — flagship setup went from ~8 min to
            # seconds with one clean H2D per tensor.
            arr = _HostInitBuffer(shape)
            try:
                initializer(InitDesc(name, var_attrs.get(name)), arr)
                a = arr._np
            except Exception:
                # a custom initializer that uses more NDArray surface
                # than `.shape` + `arr[:] = x` (in-place ops, reads,
                # out= random calls) gets the real thing — correct but
                # slow when tunnel weather is bad
                from ..ndarray import zeros as nd_zeros

                nd = nd_zeros(shape)
                initializer(InitDesc(name, var_attrs.get(name)), nd)
                a = np.asarray(nd.data)
            if cast is not None and name.endswith("weight"):
                a = a.astype(cast)
            return jax.device_put(a, self._param_sharding[name])

        plan = None if get_env("HOST_INIT", 0, int) else \
            _device_init_plan(
                initializer, [(n, tuple(shape_of[n]), var_attrs.get(n))
                              for n in self.param_names])
        if plan is not None:
            # all params recognized: generate masters ON CHIP in one
            # jitted program, keyed by (global mx.random stream, seed,
            # crc32(name)).  Drawing next_key() preserves the
            # mx.random.seed reproducibility contract (random.py:30) the
            # host-numpy path gets for free: reseeding gives a fresh
            # deterministic init, two constructions without reseeding
            # differ — exactly like consuming np.random
            from .. import random as _random

            base_key = jax.random.fold_in(_random.next_key(), seed)

            def make_params():
                out = {}
                for n in self.param_names:
                    k = jax.random.fold_in(
                        base_key, zlib.crc32(n.encode()) & 0x7FFFFFFF)
                    a = plan[n](k, tuple(shape_of[n]))
                    if cast is not None and n.endswith("weight"):
                        a = a.astype(cast)
                    out[n] = a
                return out

            self.params = jax.jit(
                make_params,
                out_shardings={n: self._param_sharding[n]
                               for n in self.param_names})()
        else:
            self.params = {n: host_init(n, shape_of[n])
                           for n in self.param_names}
        self.aux = {n: jax.device_put(
            np.ones(s, np.float32) if n.endswith(("var",))
            else np.zeros(s, np.float32), rep)
            for n, s in zip(aux_names, aux_shapes)}
        # optimizer states: ONE jitted program materializes every zero
        # buffer directly into its sharding — no per-shape dispatch, no
        # host->device transfer of 2×params of zeros
        if self._n_states:
            def make_states():
                return {
                    n: tuple(jnp.zeros(
                        self.params[n].shape,
                        self._state_dtype or self.params[n].dtype)
                        for _ in range(self._n_states))
                    for n in self.param_names}

            out_sh = {n: tuple(self._state_sharding[n]
                               for _ in range(self._n_states))
                      for n in self.param_names}
            self.opt_states = jax.jit(
                make_states, out_shardings=out_sh)()
        else:
            self.opt_states = {n: () for n in self.param_names}
        self.optimizer_state_bytes()  # publish the footprint gauges
        self._key = jax.random.PRNGKey(seed)

        # fp8 amax-history state: one {x, w, g} window per FC site, in
        # topo order (= trace order under the symbol interpreter, so
        # site i is the same layer every step).  Tiny and replicated.
        if self._quant_recipe is not None:
            from ..quant import fp8 as _fp8

            self.quant_state = tuple(
                jax.device_put(_fp8.init_site_state(self._quant_recipe),
                               rep)
                for _ in range(self._quant_sites))
        self._last_scales = None  # quant_info() rescale detection

        # ---- on-device metrics (docs/input_pipeline.md) -----------------
        # metrics= folds per-step metric partials (e.g. correct-count +
        # sample-count) into a donated 2-element device buffer INSIDE the
        # step program — read_metrics() is then the only host readback,
        # once per window/epoch instead of per batch.
        self.metric = None
        self._metric_spec = None
        self._metric_buf = None
        self._metric_label = None
        if metrics is not None:
            from .. import metric as metric_mod

            self.metric = metric_mod.create(metrics)
            self._metric_spec = metric_mod.device_partials(self.metric)
            if self._metric_spec is None:
                raise MXNetError(
                    "metric %r has no device twin (metric."
                    "device_partials) — drop metrics= and update on host"
                    % self.metric.name)
            if not label_shapes:
                raise MXNetError(
                    "metrics= needs label_shapes (the partial pairs the "
                    "first label input with symbol output 0)")
            self._metric_label = list(label_shapes)[0]
            self._metric_buf = jax.device_put(
                np.zeros((2,), self._metric_spec[1]), rep)

        # bounded dispatch window (TP_MAX_INFLIGHT, overlap.py): each
        # call fences the step N behind via a scalar derived from its
        # outputs, so at most N steps are ever in flight
        from ..overlap import InflightRing, max_inflight

        _n_inflight = max_inflight()
        self._ring = InflightRing(_n_inflight, scope="fused") \
            if _n_inflight > 0 else None

        self._step_fn = self._build(shapes)

    # -------------------------------------------------------------- build
    def _build(self, shapes):
        import jax
        import jax.numpy as jnp

        from .collectives import (all_gather_constraint,
                                  reduce_scatter_constraint)

        telemetry.counter("jit_compile_total").inc()
        fwd = _lower_symbol(self.symbol, is_train=True, remat=self.remat)
        quant_recipe = self._quant_recipe
        if quant_recipe is not None:
            from .. import quant
        opt_op = get_op(self._opt_op)
        opt_attrs = dict(self._opt_attrs)
        n_states = self._n_states
        zero_names = frozenset(self._zero_names)
        state_sharding = dict(self._state_sharding)
        param_sharding = dict(self._param_sharding)
        bucketed = self._bucket_mb > 0
        bucket_plan = self._bucket_plan
        comm_dtype = self._comm_dtype

        adam_b1 = float(opt_attrs.get("beta1", 0.9))
        adam_b2 = float(opt_attrs.get("beta2", 0.999))
        is_adam = self._opt_op == "adam_update"

        def step(params, opt_states, aux, qstate, key, lr, t, batch):
            if is_adam:
                # Adam bias correction folded into lr, matching
                # optimizer.Adam (optimizer.py): lr·√(1-β2ᵗ)/(1-β1ᵗ)
                import jax.numpy as _jnp

                lr = lr * _jnp.sqrt(1.0 - _jnp.power(adam_b2, t)) \
                    / (1.0 - _jnp.power(adam_b1, t))
            def micro_grads(p, qs, aux_in, mb, mb_key):
                if quant_recipe is None:
                    def f(p):
                        args = dict(mb)
                        args.update(p)
                        return fwd(args, aux_in, mb_key)

                    (outs, new_aux), vjp_fn = jax.vjp(f, p)
                    ct = ([jnp.ones_like(o) for o in outs],
                          {k: jnp.zeros_like(v)
                           for k, v in new_aux.items()})
                    (g,) = vjp_fn(ct)
                    new_qs = qs
                else:
                    # fp8: differentiate jointly w.r.t. (params, state)
                    # so the backward's gradient amax — first observed
                    # during backprop — can flow out as the state
                    # cotangent (quant/fp8.py docstring)
                    def f(p, qs_in):
                        col = quant.FP8Sites(qs_in, quant_recipe)
                        with quant.matmul_context(col):
                            args = dict(mb)
                            args.update(p)
                            outs, new_aux = fwd(args, aux_in, mb_key)
                        if len(col.new_states) != len(qs_in):
                            raise MXNetError(
                                "fp8 trace consumed %d of %d planned "
                                "FullyConnected sites"
                                % (len(col.new_states), len(qs_in)))
                        return outs, new_aux, tuple(col.new_states)

                    (outs, new_aux, fstate), vjp_fn = jax.vjp(f, p, qs)
                    ct = ([jnp.ones_like(o) for o in outs],
                          {k: jnp.zeros_like(v)
                           for k, v in new_aux.items()},
                          jax.tree_util.tree_map(jnp.zeros_like, fstate))
                    g, gstate = vjp_fn(ct)
                    # merge: x/w histories refresh in the forward,
                    # the g history arrives via the backward
                    new_qs = tuple(
                        {"x": fs["x"], "w": fs["w"], "g": gs["g"]}
                        for fs, gs in zip(fstate, gstate))
                if self._grad_dtype is not None:
                    # cast at the backward boundary: accumulation and
                    # the dp all-reduce then run at half width
                    g = {n: v.astype(self._grad_dtype)
                         for n, v in g.items()}
                return g, outs, new_aux, new_qs

            if self._accum == 1:
                grads, outs, new_aux, new_qstate = micro_grads(
                    params, qstate, aux, batch, key)
            else:
                # k sequential microbatches in ONE program: grads sum,
                # moving aux threads through the scan carry, outputs
                # restack to the full batch
                k = self._accum
                stacked = {n: v.reshape((k, v.shape[0] // k)
                                        + tuple(v.shape[1:]))
                           for n, v in batch.items()}

                def body(carry, mb):
                    aux_c, gsum, qs_c, i = carry
                    g, outs, new_aux, qs_n = micro_grads(
                        params, qs_c, aux_c, mb,
                        jax.random.fold_in(key, i))
                    gsum = jax.tree_util.tree_map(
                        lambda a, b: a + b, gsum, g)
                    return (new_aux, gsum, qs_n, i + 1), outs

                gzero = {n: jnp.zeros(v.shape,
                                      self._grad_dtype or jnp.float32)
                         for n, v in params.items()}
                (new_aux, grads, new_qstate, _), outs_stacked = \
                    jax.lax.scan(
                        body, (aux, gzero, qstate, jnp.int32(0)), stacked)
                # restack an output to the full batch ONLY when merging
                # the microbatch axis reproduces the full-batch shape
                # (batch-axis outputs, incl. flattened ones like the
                # (b*S,) LM loss); anything else — reduced losses,
                # batch-free outputs — stays stacked per-microbatch
                # (k, ...) rather than being silently scrambled
                def restack(o, full_shape):
                    merged = (o.shape[0] * o.shape[1],) \
                        + tuple(o.shape[2:]) if o.ndim >= 2 else None
                    if merged == tuple(full_shape):
                        return o.reshape(merged)
                    return o

                outs = [restack(o, s) for o, s in
                        zip(outs_stacked, self._out_shapes)]

            if bucketed:
                # issue one pinned collective per bucket, in backward-
                # completion order, AFTER the accumulation scan — the
                # reduction happens once, on the summed (last-
                # microbatch) grads.  ZeRO params land reduce-scattered
                # straight into their state sharding at wire dtype.
                from .buckets import bucketed_reduce

                grads = bucketed_reduce(
                    grads, bucket_plan, param_sharding,
                    zero_names=zero_names,
                    state_sharding=state_sharding,
                    comm_dtype=comm_dtype)

            attrs = dict(opt_attrs, lr=lr)
            new_params, new_states = {}, {}
            if self._flat_opt:
                # grouped by dtype: concatenating bf16 weights with f32
                # biases would silently promote the whole buffer (and the
                # donated pytree's dtypes) to f32
                groups: Dict[Any, List[str]] = {}
                for n in params:
                    groups.setdefault(params[n].dtype, []).append(n)
                for names in groups.values():
                    flat_w = jnp.concatenate(
                        [params[n].reshape(-1) for n in names])
                    flat_g = jnp.concatenate(
                        [grads[n].astype(params[n].dtype).reshape(-1)
                         for n in names])
                    flat_s = [jnp.concatenate(
                        [opt_states[n][i].reshape(-1) for n in names])
                        for i in range(n_states)]
                    res, _ = opt_op.apply([flat_w, flat_g] + flat_s,
                                          attrs, OpContext(is_train=True))
                    off = 0
                    for n in names:
                        size = params[n].size
                        new_params[n] = res[0][off:off + size].reshape(
                            params[n].shape)
                        new_states[n] = tuple(
                            res[1 + i][off:off + size].reshape(
                                params[n].shape)
                            for i in range(n_states))
                        off += size
            else:
                for name, w in params.items():
                    g = grads[name]
                    # low-precision stored states: upcast for the
                    # update math, downcast on store
                    sts = [s.astype(w.dtype) for s in opt_states[name]]
                    if name in zero_names:
                        # ZeRO-1: the pending dp-sum gradient lands
                        # reduce-scattered in the state layout, the
                        # update runs on the owned shard only, and the
                        # new param all-gathers back to its placement.
                        # The scatter takes the grad at its WIRE dtype
                        # (before the master upcast) so bf16 grads
                        # move 1/dp of their bf16 — not f32 — bytes;
                        # bucketed grads arrived already scattered.
                        ssh = state_sharding[name]
                        if not bucketed:
                            g = reduce_scatter_constraint(g, ssh)
                        w = jax.lax.with_sharding_constraint(w, ssh)
                    g = g.astype(w.dtype)
                    res, _ = opt_op.apply([w, g] + sts,
                                          attrs, OpContext(is_train=True))
                    if name in zero_names:
                        new_params[name] = all_gather_constraint(
                            res[0], param_sharding[name])
                    else:
                        new_params[name] = res[0]
                    new_states[name] = tuple(
                        r.astype(s.dtype) for r, s in
                        zip(res[1:1 + n_states], opt_states[name]))
            return new_params, new_states, new_aux, new_qstate, outs

        dp = lambda ndim: data_parallel_spec(self.mesh, ndim)  # noqa: E731
        rep = replicated_spec(self.mesh)

        batch_shardings = {n: dp(len(s)) for n, s in shapes.items()}
        param_sh = dict(self._param_sharding)
        state_sh = {n: tuple(self._state_sharding[n]
                             for _ in range(n_states))
                    for n in self.params}
        aux_sh = {n: rep for n in self.aux}
        # exact pytree (not a prefix): () when quant is off
        q_sh = tuple({"x": rep, "w": rep, "g": rep}
                     for _ in range(len(self.quant_state)))

        if self._metric_spec is None:
            return jax.jit(
                step,
                in_shardings=(param_sh, state_sh, aux_sh, q_sh, None,
                              None, None, batch_shardings),
                out_shardings=(param_sh, state_sh, aux_sh, q_sh, None),
                donate_argnums=(0, 1, 2))

        metric_fn = self._metric_spec[0]
        metric_label = self._metric_label

        def step_with_metrics(params, opt_states, aux, mbuf, qstate, key,
                              lr, t, batch):
            new_params, new_states, new_aux, new_qstate, outs = step(
                params, opt_states, aux, qstate, key, lr, t, batch)
            # same XLA program as the update: draining the buffer later
            # also fences the whole step
            s, c = metric_fn(batch[metric_label], outs[0])
            mbuf = mbuf + jnp.stack([s, c]).astype(mbuf.dtype)
            return new_params, new_states, new_aux, mbuf, new_qstate, outs

        return jax.jit(
            step_with_metrics,
            in_shardings=(param_sh, state_sh, aux_sh, rep, q_sh, None,
                          None, None, batch_shardings),
            out_shardings=(param_sh, state_sh, aux_sh, rep, q_sh, None),
            donate_argnums=(0, 1, 2, 3))

    # ---------------------------------------------------------------- call
    def __call__(self, batch: Dict[str, Any]):
        """Run one step; returns the symbol outputs (sharded on dp)."""
        import jax
        import jax.numpy as jnp

        telemetry.counter("fused_steps_total").inc()
        _tctx = tracing.train_context()
        _tr0 = time.monotonic() if _tctx is not None else 0.0
        self.num_update += 1
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        self._key = jax.random.fold_in(self._key, 1)
        vals = {}
        for n, v in batch.items():
            from ..ndarray.ndarray import NDArray

            if isinstance(v, NDArray):
                a = v.data
            elif isinstance(v, jax.Array):
                a = v  # already device-resident: no host round-trip
            else:
                a = jnp.asarray(np.asarray(v, dtype=np.float32))
            vals[n] = a
        if self._metric_spec is not None:
            (self.params, self.opt_states, self.aux, self._metric_buf,
             self.quant_state, outs) = self._step_fn(
                self.params, self.opt_states, self.aux,
                self._metric_buf, self.quant_state, self._key,
                jnp.float32(lr), jnp.float32(self.num_update), vals)
        else:
            (self.params, self.opt_states, self.aux, self.quant_state,
             outs) = self._step_fn(
                self.params, self.opt_states, self.aux, self.quant_state,
                self._key, jnp.float32(lr),
                jnp.float32(self.num_update), vals)
        if _tctx is not None and self._bucket_mb > 0:
            # the bucketed collectives live INSIDE the jitted step, so
            # the host-observable span is the dispatch of the program
            # that carries them, annotated with the static plan — the
            # overlap fraction is the compile-time bound, the fence
            # span shows where the wire time actually surfaces
            tracing.record(
                _tctx, "train.collective", _tr0, time.monotonic(),
                {"buckets": self._bucket_plan.num_buckets,
                 "bytes": self._bucket_plan.total_bytes,
                 "overlap_fraction":
                     round(self._bucket_plan.overlap_fraction, 4)})
        if self._ring is not None and outs:
            from ..overlap import fence_handle

            # bounded async dispatch: fence the step TP_MAX_INFLIGHT
            # behind on a scalar derived from ITS outputs (outputs are
            # not donated, so the handle survives later steps)
            self._ring.push(fence_handle(outs[0]))
        return outs

    # -------------------------------------------------------------- fence
    def sync(self) -> float:
        """True execution fence: host-read one scalar that depends on
        the latest parameter update.  Uses the SMALLEST parameter —
        every param updates in the same XLA program, so any one fences
        the step, and a large readback would measure the (slow, on some
        platforms wildly variable) D2H path instead (PERF.md §1, §8c).
        """
        if self._ring is not None:
            self._ring.drain()
        name = min(self.params, key=lambda n: self.params[n].size)
        return float(np.asarray(self.params[name]).ravel()[0])

    # ------------------------------------------------------------ metrics
    def read_metrics(self):
        """Drain the on-device metric buffer into ``self.metric`` with
        ONE host readback and return the metric.

        Call once per window/epoch — ``metric_readbacks_total`` counts
        these, O(steps/window) vs the per-batch ``update_metric`` sync.
        The buffer belongs to the latest step's XLA program, so this is
        also a true execution fence."""
        if self._metric_spec is None:
            raise MXNetError(
                "construct FusedTrainStep(metrics=...) to accumulate "
                "metrics on device")
        import jax

        vals = np.asarray(self._metric_buf)
        telemetry.counter("metric_readbacks_total").inc()
        if vals.dtype.kind in "iu":
            self.metric.sum_metric += int(vals[0])
        else:
            self.metric.sum_metric += float(vals[0])
        self.metric.num_inst += int(vals[1])
        self._metric_buf = jax.device_put(
            np.zeros((2,), self._metric_spec[1]),
            replicated_spec(self.mesh))
        return self.metric

    # --------------------------------------------------------------- quant
    def quant_info(self):
        """Host snapshot of the fp8 site states (docs/quantization.md):
        per-site delayed scales and rolling amax, published to the
        ``quant_scale`` gauges; sites whose scale moved since the last
        snapshot bump ``quant_amax_rescales_total``.  One D2H readback
        per call — invoke per logging window, not per step.  Returns
        None when the fp8 path is off."""
        if self._quant_recipe is None:
            return None
        from ..quant import fp8 as _fp8

        rec = self._quant_recipe
        fmt_max = {"x": _fp8.E4M3_MAX, "w": _fp8.E4M3_MAX,
                   "g": _fp8.E5M2_MAX}
        sites = []
        scales = {}
        for i, st in enumerate(self.quant_state):
            entry = {"site": i}
            for role in ("x", "w", "g"):
                hist = np.asarray(st[role])
                amax = float(hist.max())
                scale = amax * rec.margin / fmt_max[role] \
                    if amax > 0.0 else 1.0
                entry[role] = {"amax": amax, "scale": scale}
                scales[(i, role)] = scale
                telemetry.gauge("quant_scale",
                                {"site": str(i), "role": role}).set(scale)
            sites.append(entry)
        if self._last_scales is not None:
            moved = sum(1 for k, v in scales.items()
                        if v != self._last_scales.get(k))
            if moved:
                telemetry.counter("quant_amax_rescales_total").inc(moved)
        self._last_scales = scales
        return {"recipe": repr(rec), "sites": sites}

    # -------------------------------------------------------------- state
    def optimizer_state_bytes(self):
        """``(logical_total, per_device)`` bytes of the optimizer state;
        refreshes the ``optimizer_state_bytes_*`` telemetry gauges.
        Under ``shard_optimizer`` the per-device share is ~1/dp (and
        1/ep for expert params) of the replicated footprint."""
        from .zero import publish_state_gauges

        return publish_state_gauges(self.opt_states, "fused")

    # ------------------------------------------------------------ buckets
    def bucket_plan(self):
        """The static gradient-comm :class:`~.buckets.BucketPlan` —
        per-bucket bytes, wire dtype, overlap bound (``.report()`` for
        the human dump, ``.to_dict()`` for bench records).  Always
        present; at ``grad_bucket_mb=0`` it describes the monolithic
        single-bucket reduction the step actually runs."""
        return self._bucket_plan

    # ------------------------------------------------------------- params
    def get_params(self):
        """Gather to host as NDArray dicts (Module-compatible)."""
        from ..ndarray.ndarray import NDArray

        arg = {n: NDArray(v) for n, v in self.params.items()}
        aux = {n: NDArray(v) for n, v in self.aux.items()}
        return arg, aux

    def set_params(self, arg_params, aux_params=None):
        import jax

        rep = replicated_spec(self.mesh)
        for n, v in (arg_params or {}).items():
            if n in self.params:
                data = v.data if hasattr(v, "data") else v
                self.params[n] = jax.device_put(
                    data.astype(self.params[n].dtype), rep)
        for n, v in (aux_params or {}).items():
            if n in self.aux:
                data = v.data if hasattr(v, "data") else v
                self.aux[n] = jax.device_put(data, rep)
