"""Gradient bucketing + comms/compute overlap (docs/comm_overlap.md).

The PyTorch-DDP / Horovod bucketing insight (Li et al., "PyTorch
Distributed", VLDB 2020) applied to a GSPMD mesh: instead of ONE
monolithic gradient all-reduce (or ZeRO reduce-scatter) issued after
the whole backward pass, the grad pytree is grouped into size-targeted
buckets in reverse-autodiff order — the order backward *completes*
gradients — and each bucket's collective is issued as soon as the
bucket is full, so communication runs concurrent with the remaining
backward compute instead of after it.

Under jit there is no imperative "issue now": the issue points are
pinned structurally.  Each bucket's values are threaded through a
shared ``lax.optimization_barrier`` token before AND after its
collective, which (a) prevents XLA's all-reduce combiner from merging
the buckets back into one monolithic collective, and (b) orders the
buckets on one logical comm stream the way DDP's dedicated NCCL
stream does.  The collectives themselves use the repo's GSPMD
spelling (``with_sharding_constraint`` — ``parallel/collectives.py``):
a replicated constraint resolves the pending dp-sum as an all-reduce;
a ZeRO state-sharding constraint resolves it as a reduce-scatter.

Bit-equality contract: at f32 wire dtype the bucketed path is
bit-identical to the monolithic one — barriers are value-identity,
concat/slice commute with the elementwise psum, and psum of a slice
equals the slice of the psum.  ``TP_GRAD_COMM_DTYPE=bf16`` opts into
halving the wire bytes (grads cast to bf16 per bucket, reduced on the
wire, upcast for the f32 update math) and is therefore only legal
with bucketing enabled — the monolithic path stays exactly the seed.

Everything in this module is pure planning + trace-time graph
building; nothing allocates device memory.
"""
from __future__ import annotations

from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry

__all__ = ["Bucket", "BucketPlan", "param_backward_order",
           "plan_buckets", "build_plan", "segment_bounds",
           "bucketed_reduce", "bucketed_psum", "resolve_comm_knobs"]


def resolve_comm_knobs(grad_bucket_mb, grad_comm_dtype):
    """Resolve the shared (bucket size, wire dtype) knob pair.

    Explicit arguments win; ``None`` falls back to ``TP_GRAD_BUCKET_MB``
    (MiB per bucket, 0 = monolithic seed path) and ``TP_GRAD_COMM_DTYPE``
    (e.g. ``bf16``; unset/f32 = reduce at the grad's own dtype).  A wire
    dtype without bucketing is rejected: the monolithic reduction is
    contractually bit-identical to the seed, so compression may only
    ride the bucketed scheduler.  Returns ``(bucket_mb, np dtype|None)``.
    """
    from ..base import MXNetError, dtype_np, get_env

    if grad_bucket_mb is None:
        grad_bucket_mb = float(get_env("GRAD_BUCKET_MB", 0, float))
    bucket_mb = float(grad_bucket_mb)
    if bucket_mb < 0:
        raise MXNetError("grad_bucket_mb must be >= 0")
    if grad_comm_dtype is None:
        grad_comm_dtype = get_env("GRAD_COMM_DTYPE") or None
    if grad_comm_dtype in ("float32", "f32"):
        grad_comm_dtype = None
    if grad_comm_dtype == "bf16":
        grad_comm_dtype = "bfloat16"
    comm_dtype = dtype_np(grad_comm_dtype) if grad_comm_dtype else None
    if comm_dtype is not None and not bucket_mb:
        raise MXNetError(
            "grad_comm_dtype=%r requires grad bucketing "
            "(grad_bucket_mb / TP_GRAD_BUCKET_MB > 0): the monolithic "
            "reduction stays bit-identical to the unbucketed path"
            % (grad_comm_dtype,))
    return bucket_mb, comm_dtype

# one bucket: param names (issue order within is irrelevant — they
# share a single pinned issue point), total elements, wire bytes
Bucket = namedtuple("Bucket", ["names", "elems", "bytes"])


def param_backward_order(symbol, param_names: Sequence[str]) -> \
        List[str]:
    """``param_names`` sorted by when backward COMPLETES their grad.

    A parameter's gradient is finished once the backward sweep has
    processed every consumer of the parameter; backward walks the topo
    order in reverse, so the grad completes when it passes the
    parameter's EARLIEST consumer.  Sorting by descending min-consumer
    position therefore yields grads in completion order — the order
    buckets should fill and issue.  Params with no consumer (dead
    inputs) sort last; ties keep declaration order for determinism.
    """
    nodes = symbol.topo_nodes()
    pos = {}
    compute_pos = 0
    first_use: Dict[str, int] = {}
    for node in nodes:
        if node.is_variable:
            continue
        for inp, _ in node.inputs:
            if inp.is_variable and inp.name not in first_use:
                first_use[inp.name] = compute_pos
        compute_pos += 1
    for i, n in enumerate(param_names):
        pos[n] = (-first_use.get(n, -1), i)
    return sorted(param_names, key=lambda n: pos[n])


def plan_buckets(items: Sequence[Tuple[str, int]], bucket_bytes: int,
                 itemsize: int) -> List[List[Tuple[str, int]]]:
    """Greedy size-targeted grouping of ``(name, elems)`` items.

    Items are taken in the given (backward-completion) order; a bucket
    closes once it holds >= ``bucket_bytes`` of payload at ``itemsize``
    bytes per element.  One oversized tensor gets a bucket of its own
    (DDP semantics — a bucket is never split below tensor granularity).
    """
    if bucket_bytes <= 0:
        return [list(items)] if items else []
    buckets: List[List[Tuple[str, int]]] = []
    cur: List[Tuple[str, int]] = []
    cur_bytes = 0
    for name, elems in items:
        cur.append((name, int(elems)))
        cur_bytes += int(elems) * itemsize
        if cur_bytes >= bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def segment_bounds(total_elems: int, bucket_mb: float,
                   itemsize: int) -> List[Tuple[int, int]]:
    """Split a flat length into contiguous ``(lo, hi)`` segments of
    ~``bucket_mb`` each — the pipeline step's flat (maxP,) grad row has
    no per-tensor boundaries worth respecting, so plain chunking is
    the bucket plan there."""
    if total_elems <= 0:
        return []
    if bucket_mb <= 0:
        return [(0, total_elems)]
    per = max(int(bucket_mb * (1 << 20) / itemsize), 1)
    return [(lo, min(lo + per, total_elems))
            for lo in range(0, total_elems, per)]


class BucketPlan:
    """The static plan: bucket composition, wire dtype, byte totals.

    ``overlap_fraction`` is the plan-level overlap bound: every bucket
    except the LAST-issued one has remaining backward compute to hide
    behind, so ``(total - last_bucket) / total`` of the wire bytes are
    overlappable.  (On the CPU test mesh XLA runs collectives inline,
    so this is the structural number the plan guarantees, not a
    measured timeline — see docs/comm_overlap.md.)
    """

    def __init__(self, buckets: Sequence[Bucket], wire_dtype,
                 bucket_mb: float, kind: str):
        self.buckets = tuple(buckets)
        self.wire_dtype = np.dtype(wire_dtype)
        self.bucket_mb = float(bucket_mb)
        self.kind = kind  # "all_reduce" | "reduce_scatter" | "psum"

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    @property
    def total_bytes(self) -> int:
        return sum(b.bytes for b in self.buckets)

    @property
    def overlap_fraction(self) -> float:
        total = self.total_bytes
        if total <= 0 or len(self.buckets) < 2:
            return 0.0
        return (total - self.buckets[-1].bytes) / float(total)

    def to_dict(self) -> Dict[str, Any]:
        return {"buckets": self.num_buckets,
                "bucket_mb": self.bucket_mb,
                "wire_dtype": self.wire_dtype.name,
                "kind": self.kind,
                "grad_comm_bytes": self.total_bytes,
                "overlap_fraction": round(self.overlap_fraction, 4),
                "bucket_bytes": [b.bytes for b in self.buckets]}

    def report(self) -> str:
        """Human-readable plan dump (the dryrun prints this)."""
        lines = ["grad bucket plan: %d bucket(s), %s wire, %s, "
                 "%.2f MiB total, overlap bound %.0f%%"
                 % (self.num_buckets, self.wire_dtype.name, self.kind,
                    self.total_bytes / float(1 << 20),
                    100.0 * self.overlap_fraction)]
        for i, b in enumerate(self.buckets):
            head = ", ".join(b.names[:3])
            if len(b.names) > 3:
                head += ", … +%d" % (len(b.names) - 3)
            lines.append("  bucket %d: %7.3f MiB  %d tensor(s)  [%s]"
                         % (i, b.bytes / float(1 << 20), len(b.names),
                            head))
        return "\n".join(lines)

    def publish(self, scope: str) -> None:
        """Expose the plan through the telemetry registry."""
        if not telemetry.enabled():
            return
        lab = {"scope": scope}
        telemetry.counter("grad_comm_buckets_total", lab).inc(
            self.num_buckets)
        telemetry.counter("grad_comm_bytes", lab).inc(self.total_bytes)
        telemetry.gauge("grad_comm_overlap_fraction", lab).set(
            self.overlap_fraction)


def build_plan(items: Sequence[Tuple[str, int]], bucket_mb: float,
               wire_dtype, kind: str) -> BucketPlan:
    """Plan buckets over ``(name, elems)`` items already in backward-
    completion order.  ``bucket_mb <= 0`` plans the monolithic single
    bucket (reporting-only — the caller keeps the unbucketed path)."""
    wire = np.dtype(wire_dtype)
    groups = plan_buckets(items, int(bucket_mb * (1 << 20)),
                          wire.itemsize)
    buckets = [Bucket(tuple(n for n, _ in g),
                      sum(e for _, e in g),
                      sum(e for _, e in g) * wire.itemsize)
               for g in groups]
    return BucketPlan(buckets, wire, bucket_mb, kind)


# ---------------------------------------------------------------------------
# trace-time schedulers
# ---------------------------------------------------------------------------


def _chain(vals, token):
    """Pin an issue point: thread ``vals`` and the comm-stream token
    through ONE optimization_barrier, so XLA can neither sink these
    values past the barrier nor merge collectives across it."""
    from jax import lax

    flat = list(vals) + [token]
    flat = lax.optimization_barrier(tuple(flat))
    return list(flat[:-1]), flat[-1]


def bucketed_reduce(grads: Dict[str, Any], plan: BucketPlan,
                    grad_sharding: Dict[str, Any],
                    zero_names=frozenset(),
                    state_sharding: Optional[Dict[str, Any]] = None,
                    comm_dtype=None) -> Dict[str, Any]:
    """Issue one pinned collective group per bucket over a grad dict
    (the ``FusedTrainStep`` path; runs inside jit tracing).

    Per bucket, in plan (= backward-completion) order: grads cast to
    the wire dtype, barrier-pinned, then resolved per tensor — names
    in ``zero_names`` reduce-scatter into their ZeRO state sharding
    (``state_sharding[name]``), everything else all-reduces via the
    grad's own sharding constraint (replicated params → plain
    all-reduce; tp/ep-sharded params keep their placement).  The
    tensors of one bucket sit between the same two barriers, so XLA's
    all-reduce combiner may fuse them into ONE collective but can
    never merge across buckets.  Deliberately NOT concatenated by
    hand: per-tensor collectives keep every downstream fusion shape
    identical to the monolithic program, which is what makes the f32
    wire path bit-identical.  Returned grads stay in the wire dtype;
    the optimizer upcasts.
    """
    import jax.numpy as jnp

    from .collectives import (all_reduce_constraint,
                              reduce_scatter_constraint)

    out: Dict[str, Any] = {}
    token = jnp.zeros((), jnp.float32)
    for bucket in plan.buckets:
        wire = []
        for n in bucket.names:
            g = grads[n]
            if comm_dtype is not None and g.dtype != comm_dtype:
                g = g.astype(comm_dtype)
            wire.append(g)
        wire, token = _chain(wire, token)
        reduced = []
        for n, g in zip(bucket.names, wire):
            if n in zero_names:
                reduced.append(reduce_scatter_constraint(
                    g, state_sharding[n]))
            else:
                reduced.append(all_reduce_constraint(
                    g, grad_sharding[n]))
        reduced, token = _chain(reduced, token)
        out.update(zip(bucket.names, reduced))
    return out


def bucketed_psum(vec, bounds: Sequence[Tuple[int, int]], axis_names,
                  comm_dtype=None):
    """Segment-bucketed ``lax.psum`` of a flat grad row (the
    ``SymbolPipelineTrainStep`` path; runs inside shard_map tracing).

    Issue order is DESCENDING offset: the flat row packs params in
    topo order, so high offsets belong to late-forward layers whose
    grads complete first in backward.  psum of a slice == slice of the
    psum, so at f32 wire this is bit-identical to one monolithic psum.

    The reduced segments are stitched back with dynamic_update_slice
    rather than concatenate: XLA's instruction fusion pulls a
    concatenate INTO the downstream optimizer-update loop fusion,
    which changes its codegen (and hence FMA contraction) relative to
    the monolithic program's single-psum parameter — 1-ulp drift that
    breaks the bit-equality contract.  The DUS chain stays outside the
    update fusion, so the update consumes one contiguous buffer with
    the exact fusion shape of the unbucketed program.
    """
    import jax.numpy as jnp
    from jax import lax

    from .collectives import all_reduce

    if len(bounds) <= 1 and comm_dtype is None:
        return all_reduce(vec, axis_names)
    token = jnp.zeros((), jnp.float32)
    out = jnp.zeros(vec.shape, vec.dtype)
    for i in range(len(bounds) - 1, -1, -1):
        lo, hi = bounds[i]
        seg = vec[lo:hi]
        if comm_dtype is not None:
            seg = seg.astype(comm_dtype)
        (seg,), token = _chain([seg], token)
        seg = all_reduce(seg, axis_names)
        (seg,), token = _chain([seg], token)
        out = lax.dynamic_update_slice(out, seg.astype(vec.dtype), (lo,))
    return out
