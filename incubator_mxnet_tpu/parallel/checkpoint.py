"""Sharded checkpoint/resume for the fused trainer (orbax-backed).

The reference checkpoint format is two host files — symbol JSON +
`.params` NDArray dict (``model.py:340-370``) — which this repo keeps
for API parity (`model.save_checkpoint`, `Module.save_checkpoint`).
At pod scale that format forces a full gather to host; this module adds
the TPU-native path: orbax writes each shard from the device that owns
it and restores onto the step's shardings, so checkpoints scale with
the mesh (the standard jax production pattern).

State saved: params, optimizer states, aux (BN moving stats), and
``num_update`` — everything `FusedTrainStep` (or, via its
stage-stacked flat buffers, `SymbolPipelineTrainStep`) needs to
resume bit-exact.

Restore is *resharding*: the target layout comes from the live step's
arrays, not the checkpoint.  A checkpoint written with replicated
optimizer state restores cleanly onto a ``shard_optimizer=True`` step
(each device reads just its ZeRO shard) and vice versa, so flipping
ZeRO-1 on or off mid-training-run is a resume, not a migration
(asserted by ``tests/test_zero.py``).

The pieces are exposed separately (``state_dict`` / ``save_state`` /
``restore_state`` / ``load_state_dict``) so ``resilience.
CheckpointManager`` can snapshot the state on the train thread and
hand the host copy to its background writer, while ``save_sharded`` /
``restore_sharded`` stay the one-call synchronous path.
"""
from __future__ import annotations

import os
from typing import Any, Dict

__all__ = ["state_dict", "load_state_dict", "save_state", "restore_state",
           "save_sharded", "restore_sharded"]


def state_dict(step) -> Dict[str, Any]:
    """The resumable state of a train step, as a pytree of live (device)
    arrays plus python scalars."""
    if hasattr(step, "flat_params"):
        # SymbolPipelineTrainStep: stage-stacked flat buffers
        return {
            "flat_params": step.flat_params,
            "opt_states": list(step.opt_states),
            "flat_aux": step.flat_aux,
            "num_update": step.num_update,
            "rng_key": step._key,
        }
    return {
        "params": dict(step.params),
        "opt_states": {k: list(v) for k, v in step.opt_states.items()},
        "aux": dict(step.aux),
        "num_update": step.num_update,
        # the folded PRNG key: without it a resumed run draws a
        # different dropout/noise stream than the uninterrupted one
        "rng_key": step._key,
    }


def load_state_dict(step, state: Dict[str, Any]) -> None:
    """Assign a restored state dict back onto ``step`` in place."""
    if hasattr(step, "flat_params"):
        step.flat_params = state["flat_params"]
        step.opt_states = tuple(state["opt_states"])
        step.flat_aux = state["flat_aux"]
        step.num_update = int(state["num_update"])
        step._key = state["rng_key"]
        return
    step.params = dict(state["params"])
    step.opt_states = {k: tuple(v)
                       for k, v in state["opt_states"].items()}
    step.aux = dict(state["aux"])
    step.num_update = int(state["num_update"])
    step._key = state["rng_key"]


def save_state(path: str, state: Dict[str, Any]) -> None:
    """Write a state pytree (device arrays or host snapshots) to ``path``
    — a directory; created/overwritten."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckpt:
        ckpt.save(path, state, force=True)


def restore_state(path: str, step) -> Dict[str, Any]:
    """Read a checkpoint back, resharded onto the LIVE layout of ``step``:
    the restore template carries the step's current shardings, so every
    shard lands directly on its owning device."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    template = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array) else x,
        state_dict(step))
    with ocp.StandardCheckpointer() as ckpt:
        return ckpt.restore(path, template)


def save_sharded(path: str, step) -> None:
    """Write a sharded checkpoint of a ``FusedTrainStep`` to ``path``
    (a directory; created/overwritten)."""
    save_state(path, state_dict(step))


def restore_sharded(path: str, step) -> None:
    """Restore a checkpoint IN PLACE onto ``step``, preserving its
    per-parameter shardings (tp-partitioned params restore partitioned)."""
    load_state_dict(step, restore_state(path, step))
