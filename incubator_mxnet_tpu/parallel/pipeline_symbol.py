"""Pipeline-parallel training of ARBITRARY Symbols.

Round-4's ``PipelineTrainStep`` (pipeline.py) pipelines one hardcoded
transformer family; this module stage-partitions ANY layered Symbol —
the TPU-native generalization of the reference's group2ctx placement
machinery (``src/executor/graph_executor.cc:279-393`` AssignContext +
``_CrossDeviceCopy``): the topo order is cut at single-live-tensor
boundaries into L contiguous stages, device *i* holds stage *i*'s
parameters (packed into one flat row of a (L, maxP) buffer sharded
``P('pp')``), and ONE jitted SPMD program runs the schedule's tick
loop — ``lax.switch`` on the pipeline ``axis_index`` dispatches the
local stage body, ``lax.ppermute`` carries boundary activations
forward and cotangents backward over ICI, gradients accumulate across
microbatch ticks inside the program, and the same fused optimizer ops
as ``FusedTrainStep`` apply elementwise on the stacked flat buffers.

Key mechanics (and why):

- **Cut discovery**: a cut after topo position ``p`` is valid iff
  exactly ONE tensor produced at ≤p is consumed at >p (single boundary
  activation to ppermute) and no parameter/aux variable has consumers
  on both sides (each stage owns its weights).  Cuts are chosen to
  balance a matmul-FLOPs cost proxy.
- **Heterogeneous stages under SPMD**: every device runs the same
  program, so stage bodies become branches of one ``lax.switch``; the
  boundary activation travels flattened+padded to the widest cut
  (f32), each branch unflattening its own side's shape/dtype.
- **Explicit tick→(microbatch, direction) engine**: the schedule
  table (``pipeline.pp_schedule``) assigns every tick of every stage
  an op — idle, forward, or backward — so bubble ticks are true no-op
  branches instead of masked garbage math.  A forward tick banks its
  boundary input and pre-update aux in a stash slot; the matching
  backward tick recomputes the stage forward from those exact stashed
  inputs under ``jax.vjp``, seeds the loss cotangent with the constant
  1, sums the parameter cotangent into the flat grad row, and
  ppermutes the boundary cotangent upstream.  Per-stage gradients
  therefore accumulate in INCREASING microbatch order under BOTH
  schedules — the ``FusedTrainStep(grad_accum=M)`` oracle's order —
  which is what makes ``schedule="1f1b"`` bit-equal to ``"gpipe"``.
- **Schedules** (``schedule=`` / ``TP_PP_SCHEDULE``): ``gpipe`` runs
  all forwards then all backwards, stashing all M boundary
  activations per stage; ``1f1b`` alternates one-forward-one-backward
  after L−1−s warm-up forwards, holding at most L−s in-flight
  microbatches per stage so min(L, M) stash slots suffice (Narayanan
  et al., SC'21).  Same bubble fraction (L−1)/(M+L−1), O(L) instead
  of O(M) activation memory — see docs/pipeline.md.
- **Loss heads**: ops whose custom VJP ignores the incoming cotangent
  (``SoftmaxOutput`` family, the fused xent head — reference
  semantics) must land in the FINAL stage, where the backward seed is
  the exact constant 1; earlier stages receive real cotangents that
  must flow through the boundary.
- **Aux (BN) threading**: each stage updates its local aux at its
  forward ticks, in microbatch order — exactly
  ``FusedTrainStep(grad_accum=M)``'s sequential-scan semantics, which
  is the oracle the parity tests use — and each backward recomputes
  from the aux values its forward actually saw.
"""
from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from ..base import MXNetError, get_env
from ..lowering import _interpret
from ..ops.registry import OpContext, get_op

__all__ = ["SymbolPipelineTrainStep"]

# ops whose custom VJP ignores the incoming cotangent (analytic loss
# grads, reference semantics) — allowed in the LAST stage only, where
# the backward seed is the exact constant 1
_LOSS_HEAD_OPS = frozenset({
    "SoftmaxOutput", "LinearRegressionOutput", "MAERegressionOutput",
    "LogisticRegressionOutput", "SVMOutput", "make_loss",
    "_contrib_SoftmaxXentHead",
})


def _plan_stages(symbol, micro_shapes: Dict[str, Tuple[int, ...]],
                 n_stages: int):
    """Partition ``symbol`` into ``n_stages`` contiguous pipeline stages.

    ``micro_shapes``: input name → PER-DEVICE microbatch shape.  Returns
    the stage plan consumed by ``SymbolPipelineTrainStep._build``.
    """
    import jax

    nodes = symbol.topo_nodes()
    aux_names = set(symbol.list_auxiliary_states())
    arg_names = symbol.list_arguments()
    input_names = [n for n in arg_names if n in micro_shapes]
    param_names = [n for n in arg_names if n not in micro_shapes]
    arg_shapes, _, aux_shapes = symbol.infer_shape(**micro_shapes)
    shape_of = dict(zip(arg_names, arg_shapes))
    aux_shape_of = dict(zip(symbol.list_auxiliary_states(), aux_shapes))

    # ---- probe every entry's shape+dtype at microbatch scale ----------
    arg_structs = {n: jax.ShapeDtypeStruct(tuple(shape_of[n]), np.float32)
                   for n in arg_names}
    aux_structs = {n: jax.ShapeDtypeStruct(tuple(aux_shape_of[n]),
                                           np.float32)
                   for n in aux_names}

    id2pos = {id(n): i for i, n in enumerate(nodes)}

    def probe(arg_vals, aux_vals, key):
        env, _ = _interpret(enumerate(nodes), {}, arg_vals, aux_vals,
                            key, is_train=True, aux_names=aux_names)
        # re-key by topo position: ids are process-local, positions are
        # the stable handle the plan uses
        return {(id2pos[k[0]], k[1]): v for k, v in env.items()}

    entry_struct = jax.eval_shape(probe, arg_structs, aux_structs,
                                  jax.random.PRNGKey(0))

    compute = [(ni, n) for ni, n in enumerate(nodes) if not n.is_variable]
    cpos = {id(n): p for p, (ni, n) in enumerate(compute)}
    INF = 1 << 30

    # entry (pos, i) → producer compute-position / last consumer
    prod_at, last_use = {}, {}
    for (pos, i), st in entry_struct.items():
        node = nodes[pos]
        if not node.is_variable:
            prod_at[(pos, i)] = cpos[id(node)]
    for p, (ni, node) in enumerate(compute):
        for inp, idx in node.inputs:
            if not inp.is_variable:
                e = (id2pos[id(inp)], idx)
                last_use[e] = max(last_use.get(e, -1), p)
    out_entries = [(id2pos[id(n)], i) for n, i in symbol._outputs]
    for e in out_entries:
        if e in prod_at:
            last_use[e] = INF

    # variable consumer spans: params/aux must live in ONE stage
    var_span = {}
    for p, (ni, node) in enumerate(compute):
        for inp, idx in node.inputs:
            if inp.is_variable and inp.name not in input_names:
                lo, hi = var_span.get(inp.name, (p, p))
                var_span[inp.name] = (min(lo, p), max(hi, p))

    ncomp = len(compute)
    live_count = np.zeros(ncomp, np.int64)
    live_entry = [None] * ncomp  # the boundary entry when count == 1
    for e, q in prod_at.items():
        l = last_use.get(e, -1)
        for p in range(q, min(l, ncomp - 1)):
            live_count[p] += 1
            live_entry[p] = e
    forbidden = np.zeros(ncomp, bool)
    for lo, hi in var_span.values():
        if hi > lo:
            forbidden[lo:hi] = True

    valid = [p for p in range(ncomp - 1)
             if live_count[p] == 1 and not forbidden[p]]
    if n_stages > 1 and len(valid) < n_stages - 1:
        raise MXNetError(
            "cannot pipeline this symbol into %d stages: only %d valid "
            "single-tensor cut points (a cut needs exactly one live "
            "activation and no parameter used on both sides)"
            % (n_stages, len(valid)))

    # ---- balanced cut choice (matmul-FLOPs proxy) ---------------------
    def cost(p):
        ni, node = compute[p]
        outs = [st for e, st in entry_struct.items()
                if e[0] == id2pos[id(node)]]
        out_elems = sum(int(np.prod(s.shape)) for s in outs)
        p_elems = sum(int(np.prod(shape_of[inp.name]))
                      for inp, _ in node.inputs
                      if inp.is_variable and inp.name in param_names)
        if p_elems and outs:
            rows = max(out_elems // max(outs[0].shape[-1], 1)
                       if outs[0].shape else 1, 1)
            return float(max(p_elems * rows, out_elems))
        return float(out_elems)

    costs = [cost(p) for p in range(ncomp)]
    cum = np.cumsum(costs)
    total = float(cum[-1])
    cuts: List[int] = []
    for k in range(1, n_stages):
        tgt = total * k / n_stages
        best = None
        for j, p in enumerate(valid):
            if cuts and p <= cuts[-1]:
                continue
            # leave enough later cut points for the remaining stages
            if len(valid) - j - 1 < n_stages - 1 - k:
                continue
            d = abs(float(cum[p]) - tgt)
            if best is None or d < best[0]:
                best = (d, p)
        if best is None:
            raise MXNetError(
                "cannot balance %d pipeline stages over %d valid cuts"
                % (n_stages, len(valid)))
        cuts.append(best[1])

    bounds = [-1] + cuts + [ncomp - 1]
    stage_of_cpos = np.zeros(ncomp, np.int64)
    for s in range(n_stages):
        stage_of_cpos[bounds[s] + 1:bounds[s + 1] + 1] = s

    # loss-head ops only in the last stage (their VJPs ignore the
    # cotangent; the gate protects only the final stage)
    for p, (ni, node) in enumerate(compute):
        if node.op.name in _LOSS_HEAD_OPS and \
                stage_of_cpos[p] != n_stages - 1:
            raise MXNetError(
                "loss op %s (node %s) landed in pipeline stage %d of %d;"
                " loss heads must be in the final stage — use fewer "
                "stages or restructure the tail of the network"
                % (node.op.name, node.name, stage_of_cpos[p], n_stages))
    for e in out_entries:
        if e in prod_at and stage_of_cpos[prod_at[e]] != n_stages - 1:
            raise MXNetError("symbol output produced before the final "
                             "pipeline stage; cannot pipeline")

    # ---- per-stage structures ----------------------------------------
    stage_nodes: List[List[Tuple[int, Any]]] = []
    stage_params: List[List[Tuple[str, int, int, Tuple[int, ...]]]] = []
    stage_aux: List[List[Tuple[str, int, int, Tuple[int, ...]]]] = []
    for s in range(n_stages):
        comp = [compute[p] for p in range(bounds[s] + 1, bounds[s + 1] + 1)]
        ids = {id(n) for _, n in comp}
        vars_needed, seen = [], set()
        for _, node in comp:
            for inp, idx in node.inputs:
                if inp.is_variable and id(inp) not in seen:
                    seen.add(id(inp))
                    vars_needed.append((id2pos[id(inp)], inp))
        seg = sorted(vars_needed + [(ni, n) for ni, n in comp])
        stage_nodes.append(seg)
        po, pl = 0, []
        ao, al = 0, []
        for ni, node in seg:
            if not node.is_variable:
                continue
            nm = node.name
            if nm in param_names:
                shp = tuple(shape_of[nm])
                sz = int(np.prod(shp)) if shp else 1
                pl.append((nm, po, sz, shp))
                po += sz
            elif nm in aux_names:
                shp = tuple(aux_shape_of[nm])
                sz = int(np.prod(shp)) if shp else 1
                al.append((nm, ao, sz, shp))
                ao += sz
        stage_params.append(pl)
        stage_aux.append(al)

    boundaries = []
    for s in range(n_stages - 1):
        e = live_entry[cuts[s]]
        st = entry_struct[e]
        boundaries.append((e, tuple(st.shape), st.dtype,
                           max(int(np.prod(st.shape)), 1)))

    return {
        "nodes": nodes, "id2pos": id2pos,
        "aux_names": aux_names, "input_names": input_names,
        "param_names": param_names, "shape_of": shape_of,
        "aux_shape_of": aux_shape_of,
        "stage_nodes": stage_nodes, "stage_params": stage_params,
        "stage_aux": stage_aux, "boundaries": boundaries,
        "out_entries": out_entries,
        "max_psize": max([sum(sz for _, _, sz, _ in pl)
                          for pl in stage_params] + [1]),
        "max_asize": max([sum(sz for _, _, sz, _ in al)
                          for al in stage_aux] + [1]),
        "max_boundary": max([b[3] for b in boundaries] + [1]),
    }


class SymbolPipelineTrainStep:
    """Pipelined training of an arbitrary Symbol over a ``pp`` mesh
    axis, composing with data parallelism on the remaining axes.

    ``num_microbatches`` microbatches flow through ``mesh.shape[pp]``
    stages under ``schedule`` — ``"gpipe"`` (default; all forwards
    then all backwards) or ``"1f1b"`` (one-forward-one-backward
    steady state, O(stages) instead of O(M) in-flight activations per
    stage, bit-equal losses and parameters).  Gradients sum across
    microbatches inside one jitted step (aux/BN semantics identical
    to ``FusedTrainStep(grad_accum=M)``, the oracle its tests compare
    against), then one fused optimizer update applies on the
    stage-stacked flat parameter buffer.

    Supports the same optimizer set as ``FusedTrainStep``
    (sgd/adam/rmsprop/nag/ftrl + lr_scheduler).
    """

    def __init__(self, symbol, data_shapes: Dict[str, Any],
                 label_shapes: Optional[Dict[str, Any]] = None,
                 mesh=None, num_microbatches: int = 4,
                 axis_name: str = "pp",
                 optimizer: str = "sgd",
                 optimizer_params: Optional[Dict[str, Any]] = None,
                 initializer=None, seed: int = 0,
                 shard_optimizer: Optional[bool] = None,
                 schedule: Optional[str] = None,
                 async_loss: bool = False,
                 grad_bucket_mb: Optional[float] = None,
                 grad_comm_dtype=None):
        import jax

        from ..optimizer import fused_update_plan as _fused_update_plan
        from .fused import _device_init_plan
        from .mesh import default_mesh
        from .pipeline import PP_SCHEDULES, pp_bubble_fraction

        self.symbol = symbol
        self.mesh = mesh if mesh is not None else default_mesh()
        if axis_name not in self.mesh.axis_names:
            raise MXNetError("mesh has no %r axis" % axis_name)
        self.axis_name = axis_name
        self._L = int(self.mesh.shape[axis_name])
        self._M = int(num_microbatches)
        # tick schedule: explicit argument wins, then TP_PP_SCHEDULE
        if schedule is None:
            schedule = get_env("PP_SCHEDULE", "gpipe", str)
        schedule = str(schedule).lower()
        if schedule not in PP_SCHEDULES:
            raise MXNetError(
                "unknown pipeline schedule %r (one of %s; see "
                "docs/pipeline.md)" % (schedule,
                                       ", ".join(PP_SCHEDULES)))
        self.schedule = schedule
        # async_loss=True defers the per-step host read of the loss:
        # __call__ returns the device scalar and a bounded in-flight
        # ring (TP_MAX_INFLIGHT, overlap.py) fences the step N behind —
        # the same dispatch window Module.fit and FusedTrainStep use.
        # Default False keeps the synchronous float return contract.
        self._async_loss = bool(async_loss)
        self._ring = None
        if self._async_loss:
            from ..overlap import InflightRing, max_inflight

            self._ring = InflightRing(max(1, max_inflight()),
                                      scope="pipeline")
        self.bubble_fraction = pp_bubble_fraction(self._L, self._M)
        if telemetry.enabled():
            telemetry.gauge(
                "pp_bubble_fraction",
                {"schedule": schedule, "scope": "pipeline"}).set(
                self.bubble_fraction)
        self._data_axes = tuple(a for a in self.mesh.axis_names
                                if a != axis_name)
        ndp = 1
        for a in self._data_axes:
            ndp *= self.mesh.shape[a]
        self._ndp = ndp

        label_shapes = label_shapes or {}
        shapes = dict(data_shapes)
        shapes.update(label_shapes)
        self.input_names = list(shapes.keys())
        self.global_batch = shapes[self.input_names[0]][0]
        if self.global_batch % (self._M * ndp):
            raise MXNetError(
                "global batch %d must divide into %d microbatches x %d "
                "data-parallel shards"
                % (self.global_batch, self._M, ndp))
        for n, s in shapes.items():
            if not s or s[0] != self.global_batch:
                raise MXNetError(
                    "pipelining slices axis 0 of every input; %r has "
                    "leading dim %s != global batch %d"
                    % (n, s[0] if s else None, self.global_batch))
        b = self.global_batch // self._M // ndp
        micro_shapes = {n: (b,) + tuple(s[1:]) for n, s in shapes.items()}
        self._micro_shapes = micro_shapes

        self._plan = _plan_stages(symbol, micro_shapes, self._L)

        # ---- optimizer resolution (FusedTrainStep's table) -----------
        opt_params = dict(optimizer_params or {})
        self.lr = float(opt_params.pop("learning_rate", 0.01))
        self.lr_scheduler = opt_params.pop("lr_scheduler", None)
        plan_upd = _fused_update_plan(optimizer, opt_params)
        if plan_upd is None:
            raise MXNetError(
                "SymbolPipelineTrainStep does not support optimizer %s"
                % optimizer)
        self._opt_op, self._n_states = plan_upd
        opt_params.setdefault("rescale_grad", 1.0 / self.global_batch)
        self._opt_attrs = opt_params
        self.num_update = 0

        # ZeRO-1 (parallel/zero.py): optimizer state for the stage-
        # stacked (L, maxP) flat buffers additionally shards the maxP
        # dim over the data axes — each dp replica owns 1/ndp of every
        # stage's m/v/momentum.  Requires maxP % ndp == 0, so the flat
        # layout pads up (the tail was already zero-padding).  The
        # TP_SHARD_OPTIMIZER env applies when the caller did not say.
        if shard_optimizer is None:
            shard_optimizer = bool(get_env("SHARD_OPTIMIZER", 0, int))
        self._zero = bool(shard_optimizer) and self._n_states > 0 \
            and ndp > 1
        if self._zero:
            self._plan["max_psize"] = \
                -(-self._plan["max_psize"] // ndp) * ndp

        # gradient bucketing over the dp grad psum (parallel/buckets.py,
        # docs/comm_overlap.md): the flat (maxP,) grad row is reduced in
        # ~MB-sized contiguous segments, issued highest-offset first
        # (late-forward layers complete their grads first in backward)
        # and barrier-pinned so each segment's collective overlaps the
        # remaining backward ticks.  0 (default) keeps the monolithic
        # psum.  Planned AFTER the ZeRO pad so bounds cover the real row.
        from .buckets import (build_plan, resolve_comm_knobs,
                              segment_bounds)

        self._bucket_mb, self._comm_dtype = resolve_comm_knobs(
            grad_bucket_mb, grad_comm_dtype)
        wire = self._comm_dtype or np.dtype(np.float32)
        self._bucket_bounds = segment_bounds(
            self._plan["max_psize"], self._bucket_mb, wire.itemsize)
        self._bucket_plan = build_plan(
            [("flat[%d:%d)" % (lo, hi), hi - lo)
             for lo, hi in self._bucket_bounds],
            self._bucket_mb, wire, "psum")
        self._bucket_plan.publish("pipeline")

        # ---- parameters: per-stage flat rows, on-chip init -----------
        from ..initializer import InitDesc, Uniform

        initializer = initializer or Uniform(0.01)
        plan = self._plan
        L, maxP, maxA = self._L, plan["max_psize"], plan["max_asize"]
        P = jax.sharding.PartitionSpec
        self._stack_sh = jax.sharding.NamedSharding(self.mesh,
                                                    P(axis_name))
        # optimizer-state layout: stage rows over pp, and under ZeRO
        # the flat maxP dim split over every data axis
        self._state_sh = self._stack_sh if not self._zero else \
            jax.sharding.NamedSharding(
                self.mesh, P(axis_name, tuple(self._data_axes)))
        var_attrs = {node.name: (node.attrs or {})
                     for node in plan["nodes"] if node.is_variable}
        all_named = [(n, tuple(plan["shape_of"][n]), var_attrs.get(n))
                     for pl in plan["stage_params"] for n, _, _, _ in pl]
        dev_plan = None if get_env("HOST_INIT", 0, int) else \
            _device_init_plan(initializer, all_named)
        if dev_plan is not None:
            import jax.numpy as jnp

            # global-stream keyed like FusedTrainStep: mx.random.seed
            # alone reproduces the init (random.py:30 contract)
            from .. import random as _random

            base_key = jax.random.fold_in(_random.next_key(), seed)

            def make_flat():
                flat = jnp.zeros((L, maxP), jnp.float32)
                for s in range(L):
                    for n, off, sz, shp in plan["stage_params"][s]:
                        k = jax.random.fold_in(
                            base_key,
                            zlib.crc32(n.encode()) & 0x7FFFFFFF)
                        a = dev_plan[n](k, shp).astype(jnp.float32)
                        flat = flat.at[s, off:off + sz].set(a.reshape(-1))
                return flat

            self.flat_params = jax.jit(
                make_flat, out_shardings=self._stack_sh)()
        else:
            from .fused import _HostInitBuffer

            flat = np.zeros((L, maxP), np.float32)
            for s in range(L):
                for n, off, sz, shp in plan["stage_params"][s]:
                    arr = _HostInitBuffer(shp)
                    try:
                        initializer(InitDesc(n, var_attrs.get(n)), arr)
                        a = arr._np
                    except Exception:
                        from ..ndarray import zeros as nd_zeros

                        nd = nd_zeros(shp)
                        initializer(InitDesc(n, var_attrs.get(n)), nd)
                        a = np.asarray(nd.data)
                    flat[s, off:off + sz] = np.asarray(a, np.float32) \
                        .reshape(-1)
            self.flat_params = jax.device_put(flat, self._stack_sh)

        aux0 = np.zeros((L, maxA), np.float32)
        for s in range(L):
            for n, off, sz, shp in plan["stage_aux"][s]:
                v = 1.0 if n.endswith(("var",)) else 0.0
                aux0[s, off:off + sz] = v
        self.flat_aux = jax.device_put(aux0, self._stack_sh)
        if self._n_states:
            import jax.numpy as jnp

            self.opt_states = jax.jit(
                lambda: tuple(jnp.zeros((L, maxP), jnp.float32)
                              for _ in range(self._n_states)),
                out_shardings=tuple(self._state_sh
                                    for _ in range(self._n_states)))()
        else:
            self.opt_states = ()
        self.optimizer_state_bytes()  # publish the footprint gauges
        self._key = jax.random.PRNGKey(seed + 1)
        self._mem_stats = None  # lazy AOT memory analysis cache
        self.microbatch_losses = None
        self._step_fn = self._build()

    # ------------------------------------------------------------ build
    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from .mesh import shard_map_fn
        from .pipeline import pp_schedule

        plan = self._plan
        L, M = self._L, self._M
        axis = self.axis_name
        data_axes = self._data_axes
        bucket_bounds = self._bucket_bounds \
            if self._bucket_mb > 0 else None
        comm_dtype = self._comm_dtype
        maxB = plan["max_boundary"]
        maxP = plan["max_psize"]
        maxA = plan["max_asize"]
        aux_names = plan["aux_names"]
        out_entries = set(plan["out_entries"])

        # tick → (op, microbatch, arrival-slot) tables, shape (T, L);
        # each device reads its own column by pipeline axis_index
        op_np, mb_np, arr_np, n_slots = pp_schedule(self.schedule, L, M)
        n_ticks = op_np.shape[0]

        def make_stage_fwd(s):
            seg_nodes = tuple(plan["stage_nodes"][s])
            playout = tuple(plan["stage_params"][s])
            alayout = tuple(plan["stage_aux"][s])
            bin_ = plan["boundaries"][s - 1] if s > 0 else None
            bout = plan["boundaries"][s] if s < L - 1 else None

            def stage_fwd(local_p, b_in, mb, aux_flat, key):
                args = {n: local_p[off:off + sz].reshape(shp)
                        for n, off, sz, shp in playout}
                args.update(mb)
                aux_vals = {n: aux_flat[off:off + sz].reshape(shp)
                            for n, off, sz, shp in alayout}
                env = {}
                if bin_ is not None:
                    (pos, i), shp, dt, sz = bin_
                    node = plan["nodes"][pos]
                    env[(id(node), i)] = b_in[:sz].reshape(shp) \
                        .astype(dt)
                env, new_aux = _interpret(
                    seg_nodes, env, args, aux_vals, key,
                    is_train=True, aux_names=aux_names)
                if bout is not None:
                    (pos, i), shp, dt, sz = bout
                    node = plan["nodes"][pos]
                    y = env[(id(node), i)].astype(jnp.float32) \
                        .reshape(-1)
                    b_out = jnp.zeros((maxB,), jnp.float32) \
                        .at[:sz].set(y)
                    loss = jnp.zeros((1,), jnp.float32)
                else:
                    # last stage: loss only, the boundary out is a
                    # CONSTANT zeros — the incoming cotangent seed has
                    # no path through it, so garbage in the backward
                    # channel can never reach the loss-head VJPs
                    loss = jnp.zeros((1,), jnp.float32)
                    for (pos, i) in out_entries:
                        node = plan["nodes"][pos]
                        loss = loss + jnp.sum(
                            env[(id(node), i)].astype(jnp.float32))
                    b_out = jnp.zeros((maxB,), jnp.float32)
                aux_out = aux_flat
                for n, off, sz, shp in alayout:
                    aux_out = aux_out.at[off:off + sz].set(
                        new_aux[n].astype(jnp.float32).reshape(-1))
                return b_out, loss, aux_out

            return stage_fwd

        stage_fwds = [make_stage_fwd(s) for s in range(L)]
        perm_f = [(i, i + 1) for i in range(L - 1)]
        perm_b = [(i + 1, i) for i in range(L - 1)]

        def pipeline_grads(flat_p, flat_aux, data, key):
            idx = lax.axis_index(axis)
            local_p = jnp.squeeze(flat_p, 0)
            local_aux0 = jnp.squeeze(flat_aux, 0)
            op_tbl = jnp.asarray(op_np)
            mb_tbl = jnp.asarray(mb_np)
            arr_tbl = jnp.asarray(arr_np)

            def mb_key(mbi, s):
                # keyed by (microbatch, stage): the backward recompute
                # and BOTH schedules fold in identical streams
                return jax.random.fold_in(
                    jax.random.fold_in(key, mbi), s)

            zerosB = jnp.zeros((maxB,), jnp.float32)

            def run_idle(mbi, slot, fwd_st, bwd_st, stash_b, stash_aux,
                         aux_l, grad, losses):
                return (zerosB, zerosB, stash_aux, aux_l, grad, losses)

            def make_fwd(s):
                f = stage_fwds[s]

                def run(mbi, slot, fwd_st, bwd_st, stash_b, stash_aux,
                        aux_l, grad, losses):
                    mb = {k: v[mbi] for k, v in data.items()}
                    b_out, loss, aux_out = f(
                        local_p, stash_b[slot], mb, aux_l,
                        mb_key(mbi, s))
                    # bank the PRE-update aux: the backward recompute
                    # must see what this forward saw
                    stash_aux = stash_aux.at[slot].set(aux_l)
                    losses = losses.at[mbi].add(loss[0])
                    return (b_out, zerosB, stash_aux, aux_out, grad,
                            losses)

                return run

            def make_bwd(s):
                f = stage_fwds[s]

                def run(mbi, slot, fwd_st, bwd_st, stash_b, stash_aux,
                        aux_l, grad, losses):
                    mb = {k: v[mbi] for k, v in data.items()}
                    aux_in = stash_aux[slot]
                    kk = mb_key(mbi, s)

                    def f2(p, b):
                        b_out, loss, _ = f(p, b, mb, aux_in, kk)
                        return b_out, loss

                    _, vjp = jax.vjp(f2, local_p, stash_b[slot])
                    g_p, g_b = vjp((bwd_st,
                                    jnp.ones((1,), jnp.float32)))
                    grad = grad + g_p.astype(jnp.float32)
                    return (zerosB, g_b.astype(jnp.float32), stash_aux,
                            aux_l, grad, losses)

                return run

            fwd_brs = [make_fwd(s) for s in range(L)]
            bwd_brs = [make_bwd(s) for s in range(L)]

            def run_fwd(*a):
                return lax.switch(idx, fwd_brs, *a)

            def run_bwd(*a):
                return lax.switch(idx, bwd_brs, *a)

            def tick(carry, t):
                fwd_st, bwd_st, stash_b, stash_aux, aux_l, grad, \
                    losses = carry
                opc = op_tbl[t, idx]
                mbi = mb_tbl[t, idx]
                slot = jnp.mod(mbi, n_slots)
                # bank the boundary hopping in this tick BEFORE the op
                # (arrival can coincide with the consuming forward);
                # row n_slots of the stash is scratch for no-arrival
                stash_b = stash_b.at[arr_tbl[t, idx]].set(fwd_st)
                fwd_st, bwd_st, stash_aux, aux_l, grad, losses = \
                    lax.switch(opc, (run_idle, run_fwd, run_bwd),
                               mbi, slot, fwd_st, bwd_st, stash_b,
                               stash_aux, aux_l, grad, losses)
                # activations hop downstream, cotangents hop upstream,
                # every tick (idle ops send zeros nobody banks)
                fwd_st = lax.ppermute(fwd_st, axis, perm_f)
                bwd_st = lax.ppermute(bwd_st, axis, perm_b)
                return (fwd_st, bwd_st, stash_b, stash_aux, aux_l,
                        grad, losses), None

            carry = [zerosB, zerosB,
                     jnp.zeros((n_slots + 1, maxB), jnp.float32),
                     jnp.zeros((n_slots + 1, maxA), jnp.float32),
                     local_aux0,
                     jnp.zeros((maxP,), jnp.float32),
                     jnp.zeros((M,), jnp.float32)]
            if hasattr(lax, "pcast"):  # pragma: no cover - newer jax
                # fresh zeros are unvarying; mark them device-varying
                # so they are legal scan carries under shard_map
                # (index 4, the aux row, derives from flat_aux and is
                # already varying)
                vary = (axis,) + data_axes
                carry = [c if i == 4
                         else lax.pcast(c, vary, to="varying")
                         for i, c in enumerate(carry)]
            carry, _ = lax.scan(tick, tuple(carry),
                                jnp.arange(n_ticks))
            _, _, _, _, aux_l, grad, losses = carry
            # per-microbatch losses in microbatch order: only the last
            # stage added non-zeros, dp shards each saw 1/ndp of every
            # microbatch — psum over everything reassembles the batch
            losses = lax.psum(losses, (axis,) + data_axes)
            if data_axes:
                if bucket_bounds is not None:
                    # segment-bucketed dp reduction, pinned issue
                    # points (docs/comm_overlap.md); psum of a slice
                    # == slice of the psum, so f32 wire is bit-equal
                    from .buckets import bucketed_psum

                    grad = bucketed_psum(grad, bucket_bounds,
                                         data_axes, comm_dtype)
                else:
                    grad = lax.psum(grad, data_axes)
                # BN-style aux updates come from LOCAL dp-shard stats
                # (per-device BN, the reference's semantics); average
                # them so the replicated-over-dp output is well-defined
                aux_l = lax.pmean(aux_l, data_axes)
            return losses, aux_l[None], grad[None]

        P = jax.sharding.PartitionSpec
        data_spec = {n: P(None, data_axes if data_axes else None)
                     for n in self.input_names}
        shard_map = shard_map_fn()
        smap_kw = dict(mesh=self.mesh,
                       in_specs=(P(axis), P(axis), data_spec, P()),
                       out_specs=(P(), P(axis), P(axis)))
        try:
            sharded_grads = shard_map(pipeline_grads, check_vma=False,
                                      **smap_kw)
        except TypeError:  # pragma: no cover - older jax
            sharded_grads = shard_map(pipeline_grads, check_rep=False,
                                      **smap_kw)

        opt_op = get_op(self._opt_op)
        opt_attrs = dict(self._opt_attrs)
        n_states = self._n_states
        is_adam = self._opt_op == "adam_update"
        b1 = float(opt_attrs.get("beta1", 0.9))
        b2 = float(opt_attrs.get("beta2", 0.999))

        from .collectives import (all_gather_constraint,
                                  reduce_scatter_constraint)

        zero = self._zero
        zero_sh = self._state_sh

        def step(flat_p, opt_states, flat_aux, lr, t, data, key):
            if is_adam:
                lr = lr * jnp.sqrt(1.0 - jnp.power(b2, t)) \
                    / (1.0 - jnp.power(b1, t))
            losses, new_aux, g = sharded_grads(flat_p, flat_aux, data,
                                               key)
            loss = jnp.sum(losses)
            g = g.astype(flat_p.dtype)
            p_in = flat_p
            if zero:
                # ZeRO-1 on the flat buffers: the grad's pending
                # data-axis sum reduce-scatters into the owned slice,
                # the update runs shard-local, the new params
                # all-gather back to stage rows
                g = reduce_scatter_constraint(g, zero_sh)
                p_in = jax.lax.with_sharding_constraint(flat_p, zero_sh)
            res, _ = opt_op.apply(
                [p_in, g] + list(opt_states),
                dict(opt_attrs, lr=lr), OpContext(is_train=True))
            new_p = res[0]
            if zero:
                new_p = all_gather_constraint(new_p, self._stack_sh)
            return (new_p, tuple(res[1:1 + n_states]), new_aux, loss,
                    losses)

        sh = self._stack_sh
        state_sh = tuple(self._state_sh for _ in range(n_states))
        data_sh = {n: jax.sharding.NamedSharding(self.mesh, data_spec[n])
                   for n in self.input_names}
        return jax.jit(step,
                       in_shardings=(sh, state_sh, sh, None, None,
                                     data_sh, None),
                       out_shardings=(sh, state_sh, sh, None, None),
                       donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------- call
    def __call__(self, batch: Dict[str, Any]):
        """One pipelined train step; returns the SUMMED symbol outputs
        (for loss-valued heads — the fused xent head, ``MakeLoss`` —
        this is the batch loss sum; divide by your token/sample count)."""
        import jax
        import jax.numpy as jnp

        M = self._M
        self.num_update += 1
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        data = {}
        for n in self.input_names:
            v = np.asarray(batch[n])
            data[n] = jnp.asarray(v).reshape(
                (M, v.shape[0] // M) + tuple(v.shape[1:]))
        self._key, key = jax.random.split(self._key)
        (self.flat_params, self.opt_states, self.flat_aux, loss,
         self.microbatch_losses) = \
            self._step_fn(self.flat_params, self.opt_states,
                          self.flat_aux, jnp.float32(lr),
                          jnp.float32(self.num_update), data, key)
        if self._async_loss:
            # deferred: the loss scalar IS the fence handle — the ring
            # host-reads the one TP_MAX_INFLIGHT steps behind, keeping
            # the pipeline dispatched ahead instead of fencing per step
            self._ring.push(loss)
            return loss
        return float(loss)

    # ------------------------------------------------------------ fence
    def sync(self) -> float:
        if self._ring is not None:
            self._ring.drain()
        return float(np.asarray(self.flat_params[0, 0]))

    # ----------------------------------------------------------- memory
    def memory_analysis(self):
        """``CompiledMemoryStats`` for the jitted train step, computed
        AOT (jit → lower → compile on abstract shapes, no execution)
        and cached.  ``temp_size_in_bytes`` is the per-device scratch
        high-water mark — in-flight activations, stash buffers and XLA
        workspace — the quantity the 1F1B schedule shrinks."""
        if self._mem_stats is None:
            import jax
            import jax.numpy as jnp

            L, M = self._L, self._M
            maxP = self._plan["max_psize"]
            maxA = self._plan["max_asize"]
            f32 = jnp.float32
            p = jax.ShapeDtypeStruct((L, maxP), f32)
            states = tuple(jax.ShapeDtypeStruct((L, maxP), f32)
                           for _ in range(self._n_states))
            aux = jax.ShapeDtypeStruct((L, maxA), f32)
            scalar = jax.ShapeDtypeStruct((), f32)
            data = {n: jax.ShapeDtypeStruct(
                        (M, self.global_batch // M)
                        + tuple(self._micro_shapes[n][1:]), f32)
                    for n in self.input_names}
            key = jax.ShapeDtypeStruct(self._key.shape,
                                       self._key.dtype)
            self._mem_stats = self._step_fn.lower(
                p, states, aux, scalar, scalar, data, key) \
                .compile().memory_analysis()
        return self._mem_stats

    def peak_stage_bytes(self) -> int:
        """Peak per-stage temp bytes of the compiled step (XLA buffer
        assignment); publishes the ``pp_peak_stage_bytes`` gauge."""
        stats = self.memory_analysis()
        peak = int(getattr(stats, "temp_size_in_bytes", 0) or 0)
        if telemetry.enabled():
            telemetry.gauge(
                "pp_peak_stage_bytes",
                {"schedule": self.schedule,
                 "scope": "pipeline"}).set(peak)
        return peak

    # ------------------------------------------------------------ state
    def optimizer_state_bytes(self):
        """``(logical_total, per_device)`` bytes of the optimizer state;
        refreshes the ``optimizer_state_bytes_*`` telemetry gauges."""
        from .zero import publish_state_gauges

        return publish_state_gauges(list(self.opt_states), "pipeline")

    # ---------------------------------------------------------- buckets
    def bucket_plan(self):
        """The static gradient-comm :class:`~.buckets.BucketPlan` for
        the flat (maxP,) grad row's dp reduction — per-segment bytes,
        wire dtype, overlap bound.  At ``grad_bucket_mb=0`` it
        describes the monolithic single psum the step actually runs."""
        return self._bucket_plan

    # ----------------------------------------------------------- params
    def get_params(self):
        """name → NDArray for every parameter and aux state (Module /
        checkpoint-compatible)."""
        from ..ndarray.ndarray import NDArray

        flat = np.asarray(self.flat_params)
        aux = np.asarray(self.flat_aux)
        out = {}
        for s in range(self._L):
            for n, off, sz, shp in self._plan["stage_params"][s]:
                out[n] = NDArray(flat[s, off:off + sz].reshape(shp))
            for n, off, sz, shp in self._plan["stage_aux"][s]:
                out[n] = NDArray(aux[s, off:off + sz].reshape(shp))
        return out

    def set_params(self, arg_params, aux_params=None):
        """Load named params (+ optional aux) into the stage buffers."""
        import jax

        def data(v):
            return np.asarray(v.data if hasattr(v, "data") else v)

        flat = np.asarray(self.flat_params).copy()
        for s in range(self._L):
            for n, off, sz, shp in self._plan["stage_params"][s]:
                if n in arg_params:
                    flat[s, off:off + sz] = data(arg_params[n]) \
                        .astype(np.float32).reshape(-1)
        self.flat_params = jax.device_put(flat, self._stack_sh)
        if aux_params:
            aux = np.asarray(self.flat_aux).copy()
            for s in range(self._L):
                for n, off, sz, shp in self._plan["stage_aux"][s]:
                    if n in aux_params:
                        aux[s, off:off + sz] = data(aux_params[n]) \
                            .astype(np.float32).reshape(-1)
            self.flat_aux = jax.device_put(aux, self._stack_sh)

    @property
    def stage_assignment(self):
        """stage → list of op-node names (introspection/tests)."""
        return [[n.name for _, n in seg if not n.is_variable]
                for seg in self._plan["stage_nodes"]]
