"""Device-mesh construction and sharding specs.

The mesh axes convention follows the scaling-book recipe: ``dp`` (data),
``tp`` (tensor/model), optional ``pp``/``sp`` added by their stages.  On a
real pod the mesh maps onto ICI topology (jax orders devices accordingly);
under tests it is a virtual CPU mesh
(``--xla_force_host_platform_device_count``).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

__all__ = ["build_mesh", "default_mesh", "data_parallel_spec",
           "replicated_spec", "axis_size"]


def build_mesh(axes: Dict[str, int], devices=None):
    """Build a ``jax.sharding.Mesh`` with named axes, e.g.
    ``build_mesh({'dp': 4, 'tp': 2})``."""
    import jax
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    names = tuple(axes.keys())
    sizes = tuple(axes.values())
    n = 1
    for s in sizes:
        n *= s
    if n > len(devices):
        raise ValueError("mesh needs %d devices, have %d"
                         % (n, len(devices)))
    arr = np.array(devices[:n]).reshape(sizes)
    return jax.sharding.Mesh(arr, names)


def default_mesh(n_devices: Optional[int] = None):
    """1-D data-parallel mesh over all (or n) devices."""
    import jax

    devs = jax.devices()
    n = n_devices or len(devs)
    return build_mesh({"dp": n}, devs)


def data_parallel_spec(mesh, ndim: int):
    """NamedSharding: batch axis over 'dp', rest replicated."""
    import jax

    P = jax.sharding.PartitionSpec
    spec = P("dp", *([None] * (ndim - 1))) if ndim > 0 else P()
    return jax.sharding.NamedSharding(mesh, spec)


def replicated_spec(mesh):
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def shard_map_fn():
    """``jax.shard_map`` with fallback to the pre-0.8 experimental path."""
    import jax

    try:
        return jax.shard_map
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside shard_map tracing.

    ``lax.axis_size`` with fallback for jax builds that predate it:
    ``lax.psum(1, axis)`` on a Python literal takes the constant fast
    path and returns the axis size as a plain int, so the result is
    always static (usable for ``range``/``ppermute`` perm lists)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
