"""Parallelism over the device mesh — the TPU-native distribution layer.

Reference analog: the data-parallel machinery of SURVEY.md §2.4 —
``DataParallelExecutorGroup`` batch slicing + KVStore gradient aggregation +
ps-lite multi-node push/pull.  Here the idiomatic path is ONE sharded
program: ``jax.sharding.Mesh`` + ``pjit`` with XLA collectives riding ICI
(psum for gradients ≙ CommDevice reduce ≙ dist_sync server aggregation).

Components:
- :mod:`.mesh` — mesh construction + ``mesh_group`` (the ``group2ctx``
  analog for model parallelism);
- :mod:`.collectives` — psum/all_gather/reduce_scatter/ppermute wrappers;
- :mod:`.fused` — ``FusedTrainStep``: forward+backward+optimizer in one
  compiled XLA program over an arbitrary (dp, tp) mesh;
- :mod:`.sequence` — long-context sequence/context parallelism: ring
  attention (ppermute K/V rotation + online softmax) and Ulysses
  all-to-all attention.
"""
from .mesh import build_mesh, default_mesh, data_parallel_spec
from .collectives import (all_reduce, all_gather, reduce_scatter,
                          ring_permute, barrier_sync)
from .fused import FusedTrainStep
from .sequence import (attention, ring_attention, ulysses_attention,
                       sequence_parallel_attention)
from .pipeline import (pipeline_apply, pipeline_parallel_apply,
                       PipelineTrainStep, pp_bubble_fraction,
                       pp_schedule)
from .pipeline_symbol import SymbolPipelineTrainStep
from .buckets import BucketPlan, build_plan, param_backward_order
from .moe import moe_ffn, expert_parallel_moe
from .vocab_parallel import vocab_parallel_softmax_xent
from .checkpoint import save_sharded, restore_sharded

__all__ = ["build_mesh", "default_mesh", "data_parallel_spec",
           "all_reduce", "all_gather", "reduce_scatter", "ring_permute",
           "barrier_sync", "FusedTrainStep", "attention", "ring_attention",
           "ulysses_attention", "sequence_parallel_attention",
           "pipeline_apply", "pipeline_parallel_apply",
           "PipelineTrainStep", "SymbolPipelineTrainStep",
           "pp_bubble_fraction", "pp_schedule", "BucketPlan",
           "build_plan", "param_backward_order", "moe_ffn",
           "expert_parallel_moe", "save_sharded", "restore_sharded"]
