"""ZeRO-1 optimizer-state sharding plan (Rajbhandari et al., SC'20).

The reference already partitions optimizer state: each PS server owns a
key range and runs the update for its slice
(``kvstore_dist_server.h:105-230``).  This module is the TPU-native
equivalent for the one-program train steps: each parameter's optimizer
state (adam m/v, momentum, f32 masters) lives sharded over the data-
parallel mesh axes — composed with whatever model-parallel sharding the
parameter itself already has (expert weights stay ``P('ep')``-sharded,
GShard-style, and their state additionally splits over ``dp``).

The execution pattern is the GSPMD spelling of ZeRO-1: gradients are
forced into the state layout (XLA lowers the dp psum + slice into a
reduce-scatter), the elementwise update runs on the owned shard only,
and the updated parameter is forced back to its replicated/param layout
(an all-gather).  See ``collectives.reduce_scatter_constraint`` /
``all_gather_constraint`` and ``docs/zero.md``.  Under gradient
bucketing (``parallel/buckets.py``, docs/comm_overlap.md) the same
reduce-scatters issue per bucket in backward-completion order — the
state layouts planned here double as the buckets' scatter targets.

Everything here is pure planning — specs and byte math — so it is also
usable at pod-scale shapes without allocating anything (the dryrun
proves the E=2048 MoE footprint fits per-device from the plan alone).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

from .. import telemetry

__all__ = ["zero_state_spec", "shard_bytes", "state_footprint",
           "publish_state_gauges"]


def _spec_entries(spec, ndim: int):
    """PartitionSpec → per-dim tuple of axis-name tuples, length ndim."""
    entries = []
    for d in range(ndim):
        e = spec[d] if spec is not None and d < len(spec) else None
        if e is None:
            entries.append(())
        elif isinstance(e, (tuple, list)):
            entries.append(tuple(e))
        else:
            entries.append((e,))
    return entries


def zero_state_spec(mesh_axes: Dict[str, int], param_spec, shape,
                    shard_axes: Sequence[str] = ("dp",)):
    """PartitionSpec for one parameter's optimizer state, or None.

    Starts from the parameter's own spec (model-parallel placements are
    kept — an ``ep``-sharded expert weight's state stays ``ep``-sharded)
    and greedily adds each axis of ``shard_axes`` present in
    ``mesh_axes`` with size > 1 onto the first dimension it evenly
    divides and does not already occupy.  Returns None when nothing new
    could be sharded (scalar params, no free divisible dim, trivial
    axes) — the caller keeps the replicated state for that parameter.
    """
    import jax

    ndim = len(shape)
    if ndim == 0:
        return None
    entries = _spec_entries(param_spec, ndim)
    used = {a for e in entries for a in e}
    # per-dim remaining size after the existing sharding
    rem = []
    for d in range(ndim):
        n = 1
        for a in entries[d]:
            n *= mesh_axes.get(a, 1)
        rem.append(shape[d] // n if n and shape[d] % n == 0 else 0)

    added = False
    for ax in shard_axes:
        size = mesh_axes.get(ax, 1)
        if size <= 1 or ax in used:
            continue
        for d in range(ndim):
            if rem[d] and rem[d] % size == 0:
                entries[d] = entries[d] + (ax,)
                rem[d] //= size
                used.add(ax)
                added = True
                break
    if not added:
        return None
    P = jax.sharding.PartitionSpec
    norm = [None if not e else (e[0] if len(e) == 1 else e)
            for e in entries]
    while norm and norm[-1] is None:  # canonical: no trailing Nones
        norm.pop()
    return P(*norm)


def shard_bytes(mesh_axes: Dict[str, int], spec, shape,
                itemsize: int = 4) -> int:
    """Per-device bytes of one array under ``spec`` — pure math (ceil
    division per dim), valid for arbitrary pod-scale meshes without
    building them."""
    n = itemsize
    entries = _spec_entries(spec, len(shape))
    for d, s in enumerate(shape):
        div = 1
        for a in entries[d]:
            div *= mesh_axes.get(a, 1)
        n *= -(-s // div)  # ceil: uneven trailing shards pad
    return n


def state_footprint(mesh_axes: Dict[str, int],
                    param_shapes: Dict[str, Tuple[int, ...]],
                    param_specs: Optional[Dict[str, Any]] = None,
                    n_states: int = 2, itemsize: int = 4,
                    shard_axes: Sequence[str] = ("dp", "ep")):
    """Plan the optimizer-state footprint of a parameter set.

    Returns ``(replicated_per_device, sharded_per_device, specs)`` in
    bytes: what every device holds with replicated state (the seed
    behavior — each dp replica carries the FULL m/v/master set) vs under
    the ZeRO-1 plan.  ``n_states`` counts per-param state tensors
    (adam 2, momentum 1).  Abstract: nothing is allocated, so this runs
    for the E=2048 flagship on a laptop.
    """
    param_specs = param_specs or {}
    replicated = 0
    sharded = 0
    specs = {}
    for name, shape in param_shapes.items():
        base = param_specs.get(name)
        zspec = zero_state_spec(mesh_axes, base, shape,
                                shard_axes=shard_axes)
        specs[name] = zspec if zspec is not None else base
        per_state_rep = shard_bytes(mesh_axes, base, shape, itemsize)
        per_state_shard = shard_bytes(mesh_axes, specs[name], shape,
                                      itemsize)
        replicated += n_states * per_state_rep
        sharded += n_states * per_state_shard
    return replicated, sharded, specs


def publish_state_gauges(states, scope: str) -> Tuple[int, int]:
    """Set the telemetry gauges for a live set of optimizer-state arrays.

    ``states`` is any pytree of jax arrays.  Publishes
    ``optimizer_state_bytes_total`` (logical, all shards summed once —
    what ONE full copy of the state weighs) and
    ``optimizer_state_bytes_per_device`` (what each device actually
    holds), labeled by ``scope``.  Returns ``(total, per_device)``.
    """
    import jax
    import numpy as np

    total = 0
    per_device = 0
    for leaf in jax.tree_util.tree_leaves(states):
        if not hasattr(leaf, "shape"):
            continue
        itemsize = np.dtype(leaf.dtype).itemsize
        n = 1
        for s in leaf.shape:
            n *= int(s)
        total += n * itemsize
        try:
            shard_shape = leaf.sharding.shard_shape(leaf.shape)
        except Exception:
            shard_shape = leaf.shape
        m = 1
        for s in shard_shape:
            m *= int(s)
        per_device += m * itemsize
    if telemetry.enabled():
        lab = {"scope": scope}
        telemetry.gauge("optimizer_state_bytes_total", lab).set(total)
        telemetry.gauge("optimizer_state_bytes_per_device", lab).set(
            per_device)
    return total, per_device
