"""Vocab-parallel (tensor-parallel) fused softmax-cross-entropy head.

The multi-chip form of ``_contrib_SoftmaxXentHead`` (ops/nn.py): the
vocabulary projection shards over a mesh axis, each device computes
logits only for ITS vocab slice, and the softmax combines with three
tiny collectives (pmax for the global row max, psum for the normalizer,
pmax for the target logit) — the Megatron-style vocab-parallel loss,
here with the same loss-head convention as ``SoftmaxOutput``/the fused
head: backward ignores the incoming cotangent and emits the
cross-entropy gradient.

Per-device memory is O(N · V/n); dX psums over the axis, dW stays
local to each shard.  Call inside shard_map with ``w_shard`` =
(V/n, E) local slice and x/label replicated on the axis.
"""
from __future__ import annotations

import functools

__all__ = ["vocab_parallel_softmax_xent"]


def vocab_parallel_softmax_xent(x, w_shard, label, axis_name: str = "tp",
                                grad_scale: float = 1.0):
    """loss[i] = logsumexp_global(x·Wᵀ) − logit[y[i]] over a
    vocab-sharded projection; returns (N,) f32 per-position loss."""
    return _vp_sxh(axis_name, float(grad_scale))(x, w_shard, label)


@functools.lru_cache(maxsize=None)
def _vp_sxh(axis_name, grad_scale):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def _local_logits(x, w_shard):
        return jnp.matmul(x, w_shard.astype(x.dtype).T,
                          preferred_element_type=jnp.float32)

    def _fwd(x, w_shard, label):
        n_shard = w_shard.shape[0]
        idx = lax.axis_index(axis_name)
        off = idx * n_shard
        logits = _local_logits(x, w_shard)            # (N, V/n) f32
        m = lax.pmax(jnp.max(logits, axis=-1), axis_name)
        se = lax.psum(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1),
                      axis_name)
        lse = m + jnp.log(se)
        lab = label.reshape(-1).astype(jnp.int32)
        mine = (lab >= off) & (lab < off + n_shard)
        safe = jnp.clip(lab - off, 0, n_shard - 1)
        tgt_local = jnp.where(
            mine, jnp.take_along_axis(logits, safe[:, None],
                                      axis=-1)[:, 0], -jnp.inf)
        tgt = lax.pmax(tgt_local, axis_name)
        return lse - tgt, lse

    @jax.custom_vjp
    def f(x, w_shard, label):
        return _fwd(x, w_shard, label)[0]

    def f_fwd(x, w_shard, label):
        loss, lse = _fwd(x, w_shard, label)
        return loss, (x, w_shard, label, lse)

    def f_bwd(res, g):
        # loss-head convention: incoming cotangent ignored
        x, w_shard, label, lse = res
        n_shard = w_shard.shape[0]
        idx = lax.axis_index(axis_name)
        off = idx * n_shard
        logits = _local_logits(x, w_shard)
        lab = label.reshape(-1).astype(jnp.int32)
        mine = (lab >= off) & (lab < off + n_shard)
        safe = jnp.clip(lab - off, 0, n_shard - 1)
        d = jnp.exp(logits - lse[:, None])
        d = d - jax.nn.one_hot(safe, n_shard, dtype=d.dtype) \
            * mine[:, None].astype(d.dtype)
        d = (d * grad_scale).astype(x.dtype)
        wc = w_shard.astype(x.dtype)
        dx = lax.psum(jnp.matmul(d, wc), axis_name)
        dw = jnp.matmul(d.T, x, preferred_element_type=jnp.float32)
        return dx, dw.astype(w_shard.dtype), jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return f
