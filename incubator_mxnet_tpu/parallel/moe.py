"""Expert parallelism (ep): a top-k gated mixture-of-experts FFN.

Not present in the reference (v0.11 predates MoE); included because the
framework's distribution layer is first-class: experts shard one-per-
device over the ``ep`` mesh axis and tokens travel by ``lax.all_to_all``
(the standard TPU MoE dispatch — the collective rides ICI exactly like
the sequence all-to-all in :mod:`.sequence`).

Round-4 hardening (VERDICT r3 #7): top-k=2 routing with gate
renormalization, a capacity factor with explicit overflow accounting
(over-capacity assignments drop, GShard-style), the Switch/GShard
load-balancing auxiliary loss, and SPARSE dispatch — scatter-add into
an (E, C, d) capacity buffer and gather on the return trip instead of
the old dense (E, T, d) one-hot einsum, so dispatch memory/traffic
scales with capacity, not with tokens × experts.
"""
from __future__ import annotations

import functools

import numpy as np

from .mesh import axis_size as _axis_size

__all__ = ["moe_ffn", "expert_parallel_moe"]


def moe_ffn(x, gate_w, w1, w2, axis_name: str = "ep", top_k: int = 2,
            capacity_factor: float = 1.25):
    """Top-k MoE FFN on shard_map-local shards.

    x (T, d): this device's tokens.  gate_w (d, E) replicated.
    w1 (d, h), w2 (h, d): THIS device's expert (one per device,
    E = axis size).

    Routing: top-k experts per token (k=1 is Switch routing with the
    raw gate probability; k>=2 renormalizes the selected gates,
    GShard-style).  Each source device reserves C =
    ceil(capacity_factor * k * T / E) slots per expert; assignments
    beyond capacity (in token order) are dropped — their combine
    contribution is zero, matching GShard overflow semantics.

    Returns ``(out, stats)`` where out is (T, d) and stats is a dict:
    ``aux_loss`` — the E * sum_e f_e * P_e load-balancing loss with
    f_e the fraction of assignments ROUTED to e *before* capacity
    drops (the Switch-paper definition — kept-only counting would let
    a collapsed router hide behind its own overflow) and P_e the mean
    router probability, both averaged over the mesh axis;
    ``overflow`` — global fraction of assignments dropped for capacity.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops._moe_routing import (route, sparse_combine,
                                    sparse_dispatch)

    E = _axis_size(axis_name)
    T, d = x.shape
    logits = x @ gate_w                          # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    k = min(top_k, E)
    cap = int(np.ceil(capacity_factor * k * T / E))
    cap = max(cap, 1)

    # THE shared GShard routing bookkeeping (ops/_moe_routing.py) —
    # token-major capacity priority, int32 cumsum positions
    gate_vals, flat_e, onehot, keep, safe_pos = route(probs, k, cap)
    dispatch = sparse_dispatch(x, flat_e, keep, safe_pos, E, cap, k)

    # all_to_all: expert dim -> source dim; device e now holds, for
    # every source s, the <=C tokens s routed to expert e
    recv = lax.all_to_all(dispatch, axis_name, split_axis=0,
                          concat_axis=0, tiled=True)         # (E, C, d)
    h = jax.nn.relu(recv.reshape(E * cap, d) @ w1)
    y = (h @ w2).reshape(E, cap, d)
    back = lax.all_to_all(y, axis_name, split_axis=0,
                          concat_axis=0, tiled=True)         # (E, C, d)

    out = sparse_combine(back, flat_e, keep, safe_pos, gate_vals, k)

    # ---- load-balancing aux loss + overflow, averaged over the mesh.
    # f_e is the fraction of assignments ROUTED to e (pre-capacity, the
    # Switch-paper definition) — counting only kept slots would let a
    # collapsed router hide behind its own overflow drops.
    routed_frac = onehot.sum(0) / (T * k)                    # f_e local
    mean_prob = probs.mean(0)                                # P_e local
    f = lax.pmean(routed_frac, axis_name)
    P = lax.pmean(mean_prob, axis_name)
    aux_loss = E * jnp.sum(f * P)
    overflow = 1.0 - lax.pmean(keep.mean(), axis_name)
    return out, {"aux_loss": aux_loss, "overflow": overflow}


def expert_parallel_moe(mesh, x, gate_w, w1_stacked, w2_stacked,
                        axis_name: str = "ep", top_k: int = 2,
                        capacity_factor: float = 1.25):
    """Jit-compiled expert-parallel MoE over ``mesh``.

    x (T, d) sharded over ``axis_name`` on tokens; w1_stacked (E, d, h) /
    w2_stacked (E, h, d) sharded one expert per device; gate_w
    replicated.  Returns ``(out, stats)`` — see :func:`moe_ffn`.
    """
    return _build_moe(mesh, axis_name, int(top_k),
                      float(capacity_factor))(x, gate_w, w1_stacked,
                                              w2_stacked)


@functools.lru_cache(maxsize=64)
def _build_moe(mesh, axis_name, top_k, capacity_factor):
    """Cached jitted MoE — a fresh closure per call would defeat
    jax.jit's cache and retrace/recompile every step."""
    import jax

    from .mesh import shard_map_fn

    P = jax.sharding.PartitionSpec

    def body(x, gw, w1, w2):
        import jax.numpy as jnp

        return moe_ffn(x, gw, jnp.squeeze(w1, 0), jnp.squeeze(w2, 0),
                       axis_name, top_k=top_k,
                       capacity_factor=capacity_factor)

    fn = shard_map_fn()(body, mesh=mesh,
                        in_specs=(P(axis_name), P(), P(axis_name),
                                  P(axis_name)),
                        out_specs=(P(axis_name),
                                   {"aux_loss": P(), "overflow": P()}))
    return jax.jit(fn)
