"""Expert parallelism (ep): a top-1 gated mixture-of-experts FFN.

Not present in the reference (v0.11 predates MoE); included because the
framework's distribution layer is first-class: experts shard one-per-
device over the ``ep`` mesh axis and tokens travel by ``lax.all_to_all``
(the standard TPU MoE dispatch — the collective rides ICI exactly like
the sequence all-to-all in :mod:`.sequence`).

Dispatch uses per-source-slot addressing: source device *s* reserves its
own slot range on every expert, so capacity is exact (no token drops, no
cumsum bookkeeping) at the cost of an (E, T_local, d) dispatch buffer —
the right trade at the scales this targets.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["moe_ffn", "expert_parallel_moe"]


def moe_ffn(x, gate_w, w1, w2, axis_name: str = "ep"):
    """Top-1 MoE FFN on shard_map-local shards.

    x (T, d): this device's tokens.  gate_w (d, E) replicated.
    w1 (d, h), w2 (h, d): THIS device's expert (one expert per device,
    E = axis size).  Returns (T, d): each token processed by its argmax
    expert, scaled by the gate probability (top-1 Switch routing).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    E = lax.axis_size(axis_name)
    T, d = x.shape
    logits = x @ gate_w                      # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)      # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    # dispatch[e, t] = x[t] if token t routes to expert e else 0
    onehot = jax.nn.one_hot(expert, E, dtype=x.dtype)       # (T, E)
    dispatch = jnp.einsum("te,td->etd", onehot, x)          # (E, T, d)
    # all_to_all: expert dim → sources dim; device e now holds, for every
    # source s, the tokens s routed to expert e: (E_src, T, d)
    recv = lax.all_to_all(dispatch, axis_name, split_axis=0,
                          concat_axis=0, tiled=True)
    # local expert FFN over all received tokens
    h = jax.nn.relu(recv.reshape(E * T, d) @ w1)
    y = (h @ w2).reshape(E, T, d)
    # return trip: back to the token's home device
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                       # (E, T, d)
    # combine: token t's output sits in back[expert[t], t]
    combined = jnp.einsum("te,etd->td", onehot, back)
    return combined * gate[:, None]


def expert_parallel_moe(mesh, x, gate_w, w1_stacked, w2_stacked,
                        axis_name: str = "ep"):
    """Jit-compiled expert-parallel MoE over ``mesh``.

    x (T, d) sharded over ``axis_name`` on tokens; w1_stacked (E, d, h) /
    w2_stacked (E, h, d) sharded one expert per device; gate_w replicated.
    """
    return _build_moe(mesh, axis_name)(x, gate_w, w1_stacked, w2_stacked)


import functools


@functools.lru_cache(maxsize=64)
def _build_moe(mesh, axis_name):
    """Cached jitted MoE — a fresh closure per call would defeat
    jax.jit's cache and retrace/recompile every step."""
    import jax

    from .mesh import shard_map_fn

    P = jax.sharding.PartitionSpec

    def body(x, gw, w1, w2):
        import jax.numpy as jnp

        return moe_ffn(x, gw, jnp.squeeze(w1, 0), jnp.squeeze(w2, 0),
                       axis_name)

    fn = shard_map_fn()(body, mesh=mesh,
                        in_specs=(P(axis_name), P(), P(axis_name),
                                  P(axis_name)),
                        out_specs=P(axis_name))
    return jax.jit(fn)
