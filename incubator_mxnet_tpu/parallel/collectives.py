"""Collective wrappers — the transport the KVStore facade rides.

Reference analog: CommCPU/CommDevice reduce+broadcast (``comm.h``) and
ps-lite ZPush/ZPull.  TPU-native: ``lax.psum``/``all_gather``/``ppermute``
under ``shard_map`` — XLA lowers these to ICI collectives; across hosts the
same ops ride DCN via jax.distributed process groups.
"""
from __future__ import annotations

from typing import Optional, Sequence

from .. import telemetry

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "ring_permute",
           "barrier_sync", "reduce_scatter_constraint",
           "all_gather_constraint", "all_reduce_constraint"]

_KIND_LABELS = {}


def _count(kind: str, x, nbytes: Optional[int] = None) -> None:
    """Record one collective invocation + its payload bytes.

    These wrappers run inside jit/shard_map *tracing*, so counts are
    trace-time (once per compiled program), not per-execution — still the
    right signal for "what collectives does this model build, and how big".
    Bytes are counted at the value's ACTUAL element dtype (a bf16 grad
    on the wire is 2 bytes/elem, not its f32 master width); callers that
    know a tighter wire payload (reduce_scatter's per-shard output) pass
    ``nbytes`` explicitly.
    """
    if not telemetry.enabled():
        return
    lab = _KIND_LABELS.get(kind)
    if lab is None:
        lab = _KIND_LABELS[kind] = {"kind": kind}
    telemetry.counter("collective_calls_total", lab).inc()
    try:
        import numpy as np

        if nbytes is None:
            size = 1
            for s in x.shape:
                size *= int(s)
            nbytes = size * np.dtype(x.dtype).itemsize
        telemetry.counter("collective_bytes_total", lab).inc(nbytes)
    except (TypeError, ValueError, AttributeError):
        pass


def all_reduce(x, axis_name: str = "dp"):
    """Sum across a mesh axis (inside shard_map/pjit tracing)."""
    import jax

    _count("all_reduce", x)
    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name: str = "dp", axis: int = 0, tiled: bool = True):
    import jax

    _count("all_gather", x)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str = "dp", scatter_dimension: int = 0):
    import jax

    _count("reduce_scatter", x, _scatter_bytes(x, axis_name))
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def _scatter_bytes(x, axis_name) -> Optional[int]:
    """Per-shard OUTPUT bytes of a shard_map reduce-scatter: each
    device receives 1/axis_size of the input elements."""
    try:
        import numpy as np

        from .mesh import axis_size as _axis_size

        size = 1
        for s in x.shape:
            size *= int(s)
        n = int(_axis_size(axis_name)) if isinstance(axis_name, str) \
            else int(np.prod([_axis_size(a) for a in axis_name]))
        return size * np.dtype(x.dtype).itemsize // max(n, 1)
    except Exception:
        return None


def _shard_out_bytes(x, sharding) -> Optional[int]:
    """Per-shard OUTPUT bytes of a constraint-spelled reduce-scatter:
    what one device actually receives under ``sharding``."""
    try:
        import numpy as np

        shard = sharding.shard_shape(tuple(int(s) for s in x.shape))
        size = 1
        for s in shard:
            size *= int(s)
        return size * np.dtype(x.dtype).itemsize
    except Exception:
        return None


def reduce_scatter_constraint(x, sharding):
    """GSPMD spelling of a reduce-scatter (the ZeRO-1 gradient path,
    ``parallel/zero.py``): force a value that carries a pending dp-sum
    into the sharded state layout.  XLA combines the gradient psum with
    the slice into ONE reduce-scatter, so each device receives only the
    shard it owns — 1/dp of the all-reduce bytes.  Runs inside pjit
    tracing; counted once per compiled program like the shard_map
    wrappers above, at the per-shard output size."""
    import jax

    _count("reduce_scatter", x, _shard_out_bytes(x, sharding))
    return jax.lax.with_sharding_constraint(x, sharding)


def all_reduce_constraint(x, sharding):
    """GSPMD spelling of an all-reduce: force a value carrying a
    pending data-axis sum into its (usually replicated) target layout —
    XLA resolves the pending psum as ONE all-reduce at exactly this
    point.  The pinned issue points of the bucketed gradient scheduler
    (``parallel/buckets.py``) are built from this."""
    import jax

    _count("all_reduce", x)
    return jax.lax.with_sharding_constraint(x, sharding)


def all_gather_constraint(x, sharding):
    """GSPMD spelling of an all-gather: force a state-sharded value
    (the ZeRO-updated parameter shard) back into its parameter layout;
    XLA inserts the all-gather that rebuilds the full tensor on every
    device."""
    import jax

    _count("all_gather", x)
    return jax.lax.with_sharding_constraint(x, sharding)


def ring_permute(x, axis_name: str, shift: int = 1):
    """Send shard to the next device on the ring (ring-attention /
    pipeline building block)."""
    import jax

    _count("ring_permute", x)
    from .mesh import axis_size as _axis_size

    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def barrier_sync(name: str = "barrier"):
    """Host-level barrier across processes (ps-lite Barrier analog)."""
    import jax

    if telemetry.enabled():
        telemetry.counter("collective_calls_total",
                          {"kind": "barrier_sync"}).inc()
    if jax.process_count() > 1:
        from jax.experimental.multihost_utils import sync_global_devices

        sync_global_devices(name)
