"""Long-context sequence/context parallelism: ring attention + Ulysses.

The reference (MXNet v0.11) predates attention entirely — its long-sequence
story is bucketing + truncated BPTT (SURVEY.md §5.7).  The capability row
to match is "scale sequence length"; on TPU the idiomatic designs are:

- **ring attention** (`ring_attention`): Q stays resident, K/V blocks
  rotate around the mesh axis via ``lax.ppermute`` (ICI neighbor hops)
  while a streaming/flash-style online softmax accumulates the output —
  memory per chip is O(seq/n), and the K/V hop overlaps with the local
  block matmul.
- **Ulysses / all-to-all** (`ulysses_attention`): ``lax.all_to_all``
  re-shards seq→heads, full attention runs locally per head group, then
  heads→seq restores the layout.  Cheaper collectives for moderate
  sequence lengths when heads ≥ mesh axis.

Both are shard_map-ready: call them inside ``shard_map`` with the sequence
axis sharded over ``axis_name``; `sequence_parallel_attention` wraps that
for convenience.  Shapes follow (batch, heads, seq, head_dim).
"""
from __future__ import annotations

import functools
from typing import Optional

__all__ = ["attention", "flash_eligible", "ring_attention",
           "ulysses_attention", "sequence_parallel_attention"]


def flash_eligible(q_shape, k_shape) -> bool:
    """True when ``attention(impl='auto')`` would take the Pallas flash
    path for these shapes (TPU backend, 4-D, lane-aligned head_dim and
    seq lens).  THE gate — shared with ``tools/bench_lm.py``'s
    executed-FLOPs accounting so the causal halving can never drift
    from the kernel actually run."""
    import jax

    # 'axon' is this session's TPU-via-tunnel platform name
    return (jax.default_backend() in ("tpu", "axon")
            and len(q_shape) == 4 and q_shape[-1] % 128 == 0
            and q_shape[-2] % 128 == 0 and k_shape[-2] % 128 == 0)


def _neg_inf(dtype):
    import jax.numpy as jnp
    import numpy as np

    return jnp.asarray(np.finfo(np.dtype(dtype).name if
                                np.dtype(dtype).kind == "f"
                                else "float32").min, dtype)


def attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
              q_offset=0, k_offset=0, impl: str = "auto"):
    """Softmax attention on local shards (the oracle and the building
    block).  ``q_offset``/``k_offset`` are the GLOBAL positions of the
    first row/column — causal masking stays correct when q and k are
    shards of a longer sequence.

    ``impl``: ``"xla"`` materializes the score matrix (the oracle);
    ``"flash"`` uses the Pallas TPU flash-attention kernel (O(s) memory —
    measured on-chip: s=16384 runs where the materialized path OOMs,
    PERF.md); ``"auto"`` picks flash on a TPU backend when the shape
    qualifies (4-D, no offsets, lane-aligned head_dim).
    """
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    # offsets may be TRACED values (lax.axis_index arithmetic under
    # shard_map) — only CONCRETE zeros qualify for the flash path
    def _zero(off):
        import numpy as np

        if isinstance(off, (int, np.integer)):
            return int(off) == 0
        try:
            return bool(off == 0)  # concrete array scalars
        except Exception:  # traced value: not concretizable
            return False

    use_flash = impl == "flash"
    if use_flash and not (_zero(q_offset) and _zero(k_offset)):
        raise ValueError("impl='flash' does not support q_offset/"
                         "k_offset (the kernel masks from local "
                         "position 0); use impl='xla' for shard-offset "
                         "causal masking")
    if impl == "auto":
        use_flash = (_zero(q_offset) and _zero(k_offset)
                     and flash_eligible(q.shape, k.shape))
    if use_flash:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes, flash_attention)

        # kernel defaults (128-blocks) underuse the MXU: a 512-block
        # sweep measured 3.0x faster fwd+bwd at B=8,H=16,S=2048,D=128
        # on v5e (17ms vs 51ms; 1024 and mixed blocks were worse) —
        # PERF.md §11.  Blocks must divide the (128-aligned) seq lens.
        def _blk(s):
            return max(b for b in (512, 256, 128) if s % b == 0)

        bq, bk = _blk(q.shape[-2]), _blk(k.shape[-2])
        bs = BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
            block_q_dq=bq)
        return flash_attention(q, k, v, causal=causal, sm_scale=scale,
                               block_sizes=bs)
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[-2])
        ki = k_offset + jnp.arange(k.shape[-2])
        s = jnp.where(qi[:, None] >= ki[None, :], s, _neg_inf(s.dtype))
    p = jnp.exp(s - s.max(-1, keepdims=True))
    return jnp.einsum("...qk,...kd->...qd", p / p.sum(-1, keepdims=True),
                      v)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   scale: Optional[float] = None):
    """Ring self-attention over a sharded sequence axis.

    Call inside shard_map: q/k/v are the LOCAL sequence shards
    (batch, heads, seq/n, d).  K/V rotate n−1 hops around the ring
    (``ppermute``); an online softmax (running max ``m``, normalizer
    ``l``, accumulator ``o`` — the flash-attention recurrence) makes the
    streaming accumulation exact, not approximate.

    Training-safe: a ``jax.custom_vjp`` backward runs a SECOND ring pass
    that recomputes each hop's score block from the saved per-row
    logsumexp (the flash-attention backward) with the dK/dV accumulators
    riding the ring alongside their K/V blocks — per-device memory stays
    O(seq/n) in backward too, instead of reverse-mode-through-
    ``fori_loop`` checkpointing every hop's rotated K/V (O(global seq),
    the round-3 VERDICT §5.7 gap)."""
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    return _ring_attention_vjp(axis_name, bool(causal), float(scale))(
        q, k, v)


def _ring_fwd_pass(q, k, v, axis_name, causal, scale):
    """Online-softmax ring forward; returns (out, lse) with lse the
    per-row logsumexp of the GLOBAL score row (the flash residual)."""
    import jax.numpy as jnp
    from jax import lax

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    bq = q.shape[-2]
    neg = _neg_inf(jnp.float32)

    q32 = q.astype(jnp.float32)
    # derive the carries from q so they inherit its varying ('sp') axes —
    # fresh jnp.zeros would be unvarying and reject the scan carry
    m = jnp.full_like(q32[..., 0], neg)
    l = jnp.zeros_like(q32[..., 0])
    o = jnp.zeros_like(q32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    q_off = idx * bq

    def body(step, carry):
        kk, vv, m, l, o = carry
        # block (kk, vv) originated on ring neighbor (idx - step) mod n
        owner = (idx - step) % n
        s = jnp.einsum("...qd,...kd->...qk", q32,
                       kk.astype(jnp.float32)) * scale
        if causal:
            qi = q_off + jnp.arange(bq)
            ki = owner * kk.shape[-2] + jnp.arange(kk.shape[-2])
            s = jnp.where(qi[:, None] >= ki[None, :], s, neg)
        m_new = jnp.maximum(m, s.max(-1))
        # fully-masked rows: keep exp argument finite
        p = jnp.exp(s - jnp.where(m_new == neg, 0.0, m_new)[..., None])
        if causal:
            p = jnp.where((qi[:, None] >= ki[None, :]), p, 0.0)
        corr = jnp.where(m == neg, 0.0,
                         jnp.exp(m - jnp.where(m_new == neg, 0.0, m_new)))
        l = l * corr + p.sum(-1)
        o = o * corr[..., None] + jnp.einsum(
            "...qk,...kd->...qd", p, vv.astype(jnp.float32))
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return kk, vv, jnp.maximum(m, m_new), l, o

    _, _, m, l, o = lax.fori_loop(0, n, body, (k, v, m, l, o))
    out = (o / jnp.where(l == 0.0, 1.0, l)[..., None]).astype(q.dtype)
    # fully-masked rows (l == 0): lse = +inf so exp(s - lse) == 0 in bwd
    lse = jnp.where(l == 0.0, jnp.inf, m + jnp.log(
        jnp.where(l == 0.0, 1.0, l)))
    return out, lse


@functools.lru_cache(maxsize=None)
def _ring_attention_vjp(axis_name, causal, scale):
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.custom_vjp
    def f(q, k, v):
        return _ring_fwd_pass(q, k, v, axis_name, causal, scale)[0]

    def f_fwd(q, k, v):
        out, lse = _ring_fwd_pass(q, k, v, axis_name, causal, scale)
        return out, (q, k, v, out, lse)

    def f_bwd(res, do):
        q, k, v, out, lse = res
        n = lax.axis_size(axis_name)
        idx = lax.axis_index(axis_name)
        bq = q.shape[-2]
        neg = _neg_inf(jnp.float32)
        q32 = q.astype(jnp.float32)
        do32 = do.astype(jnp.float32)
        # delta[r] = Σ_d dO[r,d]·O[r,d] — the softmax-jacobian row term
        delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)

        perm = [(i, (i + 1) % n) for i in range(n)]
        q_off = idx * bq
        dq0 = jnp.zeros_like(q32)
        dk0 = jnp.zeros_like(q32, shape=k.shape)
        dv0 = jnp.zeros_like(q32, shape=v.shape)

        def body(step, carry):
            kk, vv, dk, dv, dq = carry
            owner = (idx - step) % n
            kk32 = kk.astype(jnp.float32)
            s = jnp.einsum("...qd,...kd->...qk", q32, kk32) * scale
            if causal:
                qi = q_off + jnp.arange(bq)
                ki = owner * kk.shape[-2] + jnp.arange(kk.shape[-2])
                s = jnp.where(qi[:, None] >= ki[None, :], s, neg)
            # exact probabilities from the saved logsumexp
            p = jnp.exp(s - lse[..., None])
            dv_c = jnp.einsum("...qk,...qd->...kd", p, do32)
            dp = jnp.einsum("...qd,...kd->...qk", do32,
                            vv.astype(jnp.float32))
            ds = p * (dp - delta[..., None]) * scale
            dq = dq + jnp.einsum("...qk,...kd->...qd", ds, kk32)
            dk_c = jnp.einsum("...qk,...qd->...kd", ds, q32)
            # dK/dV accumulators travel WITH their block: after n hops
            # they are back home with every device's contribution
            kk = lax.ppermute(kk, axis_name, perm)
            vv = lax.ppermute(vv, axis_name, perm)
            dk = lax.ppermute(dk + dk_c, axis_name, perm)
            dv = lax.ppermute(dv + dv_c, axis_name, perm)
            return kk, vv, dk, dv, dq

        _, _, dk, dv, dq = lax.fori_loop(
            0, n, body, (k, v, dk0, dv0, dq0))
        return (dq.astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))

    f.defvjp(f_fwd, f_bwd)
    return f


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    Inside shard_map with seq sharded on ``axis_name``: all_to_all trades
    the seq shard for a heads shard (heads must divide by the axis size),
    attention runs over the FULL sequence locally, and a reverse
    all_to_all restores the seq sharding.
    """
    from jax import lax

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if q.shape[1] % n:
        raise ValueError("heads (%d) must be divisible by axis size %d"
                         % (q.shape[1], n))
    # (b, h, s/n, d) → (b, h/n, s, d): split heads, concat sequence
    def fwd(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = fwd(q), fwd(k), fwd(v)
    out = attention(qg, kg, vg, causal=causal, scale=scale)
    # (b, h/n, s, d) → (b, h, s/n, d)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def sequence_parallel_attention(mesh, q, k, v, axis_name: str = "sp",
                                causal: bool = False,
                                scale: Optional[float] = None,
                                mode: str = "ring"):
    """Jit-compiled sequence-parallel attention over ``mesh``.

    q/k/v are GLOBAL arrays (b, h, s, d); the sequence axis is sharded
    over ``axis_name`` and the chosen kernel (``ring`` or ``ulysses``)
    runs under shard_map.
    """
    import jax

    from .mesh import shard_map_fn

    shard_map = shard_map_fn()

    P = jax.sharding.PartitionSpec
    spec = P(None, None, axis_name, None)
    fn = ring_attention if mode == "ring" else ulysses_attention
    sharded = shard_map(
        functools.partial(fn, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return jax.jit(sharded)(q, k, v)
